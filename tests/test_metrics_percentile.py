"""PercentileReservoir (core/metrics.py): the bounded p50/p90/p99
estimator the serving engine's latency telemetry rides on."""

import random

import pytest

from distributed_tensorflow_framework_tpu.core.metrics import (
    PercentileReservoir,
)


def test_exact_under_capacity():
    r = PercentileReservoir(capacity=100)
    for v in range(1, 101):  # 1..100: nearest-rank percentiles are exact
        r.add(v)
    assert r.count == 100
    assert r.percentile(50) == 50
    assert r.percentile(90) == 90
    assert r.percentile(99) == 99
    assert r.percentile(0) == 1
    assert r.percentile(100) == 100
    s = r.summary()
    assert s["count"] == 100
    assert s["mean"] == pytest.approx(50.5)
    assert s["p50"] == 50 and s["p90"] == 90 and s["p99"] == 99


def test_reservoir_sanity_over_capacity():
    # 10k uniform[0,1000) samples through a 512-slot reservoir: the
    # estimates must land in a loose band around the true percentiles
    # (Vitter's R keeps a uniform sample, so nearest-rank over it is an
    # unbiased-ish order statistic — band, not equality).
    r = PercentileReservoir(capacity=512, seed=7)
    rng = random.Random(123)
    for _ in range(10_000):
        r.add(rng.uniform(0, 1000))
    assert r.count == 10_000
    assert 400 < r.percentile(50) < 600
    assert 850 < r.percentile(90) < 950
    assert r.percentile(99) > 950
    assert r.percentile(50) <= r.percentile(90) <= r.percentile(99)


def test_deterministic_given_seed():
    def fill(seed):
        r = PercentileReservoir(capacity=16, seed=seed)
        for v in range(1000):
            r.add(float(v))
        return r.summary()

    assert fill(3) == fill(3)
    # Different seeds keep different samples (overwhelmingly likely).
    assert fill(3) != fill(4)


def test_empty_and_reset():
    r = PercentileReservoir(capacity=8)
    assert r.count == 0
    assert r.percentile(50) is None
    assert r.summary() == {
        "count": 0, "mean": None, "p50": None, "p90": None, "p99": None}
    for v in (5.0, 1.0, 9.0):
        r.add(v)
    assert r.percentile(50) == 5.0
    r.reset()
    assert r.count == 0 and r.percentile(99) is None


def test_bad_arguments():
    with pytest.raises(ValueError):
        PercentileReservoir(capacity=0)
    r = PercentileReservoir()
    r.add(1.0)
    with pytest.raises(ValueError):
        r.percentile(-1)
    with pytest.raises(ValueError):
        r.percentile(101)
