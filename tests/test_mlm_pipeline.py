"""Text-MLM TFRecord pipeline (data/text_mlm.py) against real records.

Covers the branch the synthetic fallback skips: deterministic interleave
order (the skip-count resume contract of data/tfdata.py requires identical
record order across runs — train included) and the native-reader shard
guard (fewer files than processes must raise, not silently duplicate a
shard across hosts).
"""

import os

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

from distributed_tensorflow_framework_tpu.core.config import DataConfig  # noqa: E402
from distributed_tensorflow_framework_tpu.data.text_mlm import (  # noqa: E402
    make_mlm,
)

SEQ = 16


def _write_records(root: str, *, files: int = 3, per_file: int = 8) -> None:
    os.makedirs(root, exist_ok=True)
    rng = np.random.default_rng(0)
    for f in range(files):
        path = os.path.join(root, f"mlm-{f:03d}.tfrecord")
        with tf.io.TFRecordWriter(path) as w:
            for _ in range(per_file):
                ids = rng.integers(1000, 2000, SEQ, dtype=np.int64)
                ex = tf.train.Example(features=tf.train.Features(feature={
                    "input_ids": tf.train.Feature(
                        int64_list=tf.train.Int64List(value=ids)),
                }))
                w.write(ex.SerializeToString())


@pytest.fixture(scope="module")
def record_dir(tmp_path_factory):
    root = str(tmp_path_factory.mktemp("mlm"))
    _write_records(root)
    return root


def _cfg(root: str, **kw) -> DataConfig:
    base = dict(name="text_mlm", data_dir=root, global_batch_size=4,
                seq_len=SEQ, shuffle_buffer=8, seed=11, vocab_size=2000)
    base.update(kw)
    return DataConfig(**base)


def test_mlm_tfrecord_batch_shapes(record_dir):
    ds = make_mlm(_cfg(record_dir), 0, 1, train=True)
    batch = next(ds)
    assert batch["input_ids"].shape == (4, SEQ)
    assert batch["targets"].shape == (4, SEQ)
    assert batch["attention_mask"].shape == (4, SEQ)
    # Masked positions carry the original token as target, -1 elsewhere.
    masked = batch["targets"] >= 0
    assert masked.any()
    assert (batch["targets"][~masked] == -1).all()


def test_mlm_tfrecord_determinism_and_resume(record_dir):
    ds1 = make_mlm(_cfg(record_dir), 0, 1, train=True)
    a0 = next(ds1)
    a1 = next(ds1)

    # Fresh pipeline, same seed → identical stream (train path MUST be
    # deterministic for resume to work at all).
    ds2 = make_mlm(_cfg(record_dir), 0, 1, train=True)
    b0 = next(ds2)
    np.testing.assert_array_equal(a0["input_ids"], b0["input_ids"])
    np.testing.assert_array_equal(a0["targets"], b0["targets"])

    # Snapshot after one batch, restore into a fresh pipeline → replays
    # the SECOND batch exactly, dynamic mask included.
    state = ds2.state()
    ds3 = make_mlm(_cfg(record_dir), 0, 1, train=True)
    ds3.restore(state)
    c1 = next(ds3)
    np.testing.assert_array_equal(a1["input_ids"], c1["input_ids"])
    np.testing.assert_array_equal(a1["targets"], c1["targets"])


def test_shard_guard_both_paths(record_dir):
    # 3 files across 4 processes: the native path would duplicate a shard
    # across hosts, the tf.data path would hand a host an empty shard and
    # deadlock the first collective — both must raise at construction.
    for native in (True, False):
        cfg = _cfg(record_dir, use_native_reader=native, global_batch_size=8)
        with pytest.raises(ValueError, match="one file per process"):
            make_mlm(cfg, 0, 4, train=True)


def test_eval_single_pass_padded(record_dir):
    # 24 records, batch 7 → 4 batches, last padded with all-zero token
    # rows (never masked → zero contribution to MLM sums).
    cfg = _cfg(record_dir, global_batch_size=7)
    ds = make_mlm(cfg, 0, 1, train=False)
    assert ds.cardinality == 4  # ceil(24/7)
    batches = list(ds)
    assert len(batches) == 4
    real_rows = sum(
        int((b["input_ids"] != 0).any(axis=1).sum()) for b in batches
    )
    assert real_rows == 24
    # Pad rows produce no prediction targets.
    last = batches[-1]
    pad = ~(last["input_ids"] != 0).any(axis=1)
    assert (last["targets"][pad] == -1).all()
    with pytest.raises(StopIteration):
        next(ds)


def test_native_reader_rejects_eval(record_dir):
    # The native reader has no single-pass padded mode — exact eval must
    # refuse it instead of silently recycling/dropping validation records.
    cfg = _cfg(record_dir, use_native_reader=True)
    with pytest.raises(ValueError, match="exact-eval"):
        make_mlm(cfg, 0, 1, train=False)


def test_native_reader_resume(record_dir):
    cfg = _cfg(record_dir, use_native_reader=True)
    ds1 = make_mlm(cfg, 0, 1, train=True)
    a0 = next(ds1)
    a1 = next(ds1)

    # Snapshot after batch 1 on a fresh reader; restoring it must replay
    # batch 2 with the identical dynamic mask.
    ds2 = make_mlm(cfg, 0, 1, train=True)
    b0 = next(ds2)
    np.testing.assert_array_equal(a0["input_ids"], b0["input_ids"])
    snap = ds2.state()
    ds3 = make_mlm(cfg, 0, 1, train=True)
    ds3.restore(snap)
    c1 = next(ds3)
    np.testing.assert_array_equal(a1["input_ids"], c1["input_ids"])
    np.testing.assert_array_equal(a1["targets"], c1["targets"])
