"""Text-MLM TFRecord pipeline (data/text_mlm.py) against real records.

Covers the branch the synthetic fallback skips: deterministic interleave
order (the skip-count resume contract of data/tfdata.py requires identical
record order across runs — train included) and the native-reader shard
guard (fewer files than processes must raise, not silently duplicate a
shard across hosts).
"""

import os

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

from distributed_tensorflow_framework_tpu.core.config import DataConfig  # noqa: E402
from distributed_tensorflow_framework_tpu.data.text_mlm import (  # noqa: E402
    make_mlm,
)

SEQ = 16


def _write_records(root: str, *, files: int = 3, per_file: int = 8) -> None:
    os.makedirs(root, exist_ok=True)
    rng = np.random.default_rng(0)
    for f in range(files):
        path = os.path.join(root, f"mlm-{f:03d}.tfrecord")
        with tf.io.TFRecordWriter(path) as w:
            for _ in range(per_file):
                ids = rng.integers(1000, 2000, SEQ, dtype=np.int64)
                ex = tf.train.Example(features=tf.train.Features(feature={
                    "input_ids": tf.train.Feature(
                        int64_list=tf.train.Int64List(value=ids)),
                }))
                w.write(ex.SerializeToString())


@pytest.fixture(scope="module")
def record_dir(tmp_path_factory):
    root = str(tmp_path_factory.mktemp("mlm"))
    _write_records(root)
    return root


def _cfg(root: str, **kw) -> DataConfig:
    base = dict(name="text_mlm", data_dir=root, global_batch_size=4,
                seq_len=SEQ, shuffle_buffer=8, seed=11, vocab_size=2000)
    base.update(kw)
    return DataConfig(**base)


def test_mlm_tfrecord_batch_shapes(record_dir):
    ds = make_mlm(_cfg(record_dir), 0, 1, train=True)
    batch = next(ds)
    assert batch["input_ids"].shape == (4, SEQ)
    assert batch["targets"].shape == (4, SEQ)
    assert batch["attention_mask"].shape == (4, SEQ)
    # Masked positions carry the original token as target, -1 elsewhere.
    masked = batch["targets"] >= 0
    assert masked.any()
    assert (batch["targets"][~masked] == -1).all()


def test_mlm_tfrecord_determinism_and_resume(record_dir):
    ds1 = make_mlm(_cfg(record_dir), 0, 1, train=True)
    a0 = next(ds1)
    a1 = next(ds1)

    # Fresh pipeline, same seed → identical stream (train path MUST be
    # deterministic for resume to work at all).
    ds2 = make_mlm(_cfg(record_dir), 0, 1, train=True)
    b0 = next(ds2)
    np.testing.assert_array_equal(a0["input_ids"], b0["input_ids"])
    np.testing.assert_array_equal(a0["targets"], b0["targets"])

    # Snapshot after one batch, restore into a fresh pipeline → replays
    # the SECOND batch exactly, dynamic mask included.
    state = ds2.state()
    ds3 = make_mlm(_cfg(record_dir), 0, 1, train=True)
    ds3.restore(state)
    c1 = next(ds3)
    np.testing.assert_array_equal(a1["input_ids"], c1["input_ids"])
    np.testing.assert_array_equal(a1["targets"], c1["targets"])


def test_shard_guard_both_paths(record_dir):
    # 3 files across 4 processes: the native path would duplicate a shard
    # across hosts, the tf.data path would hand a host an empty shard and
    # deadlock the first collective — both must raise at construction.
    for native in (True, False):
        cfg = _cfg(record_dir, use_native_reader=native, global_batch_size=8)
        with pytest.raises(ValueError, match="one file per process"):
            make_mlm(cfg, 0, 4, train=True)


def test_eval_single_pass_padded(record_dir):
    # 24 records, batch 7 → 4 batches, last padded with all-zero token
    # rows (never masked → zero contribution to MLM sums).
    cfg = _cfg(record_dir, global_batch_size=7)
    ds = make_mlm(cfg, 0, 1, train=False)
    assert ds.cardinality == 4  # ceil(24/7)
    batches = list(ds)
    assert len(batches) == 4
    real_rows = sum(
        int((b["input_ids"] != 0).any(axis=1).sum()) for b in batches
    )
    assert real_rows == 24
    # Pad rows produce no prediction targets.
    last = batches[-1]
    pad = ~(last["input_ids"] != 0).any(axis=1)
    assert (last["targets"][pad] == -1).all()
    with pytest.raises(StopIteration):
        next(ds)


def test_native_reader_rejects_eval(record_dir):
    # The native reader has no single-pass padded mode — exact eval must
    # refuse it instead of silently recycling/dropping validation records.
    cfg = _cfg(record_dir, use_native_reader=True)
    with pytest.raises(ValueError, match="exact-eval"):
        make_mlm(cfg, 0, 1, train=False)


def test_native_reader_resume(record_dir):
    cfg = _cfg(record_dir, use_native_reader=True)
    ds1 = make_mlm(cfg, 0, 1, train=True)
    a0 = next(ds1)
    a1 = next(ds1)

    # Snapshot after batch 1 on a fresh reader; restoring it must replay
    # batch 2 with the identical dynamic mask.
    ds2 = make_mlm(cfg, 0, 1, train=True)
    b0 = next(ds2)
    np.testing.assert_array_equal(a0["input_ids"], b0["input_ids"])
    snap = ds2.state()
    ds3 = make_mlm(cfg, 0, 1, train=True)
    ds3.restore(snap)
    c1 = next(ds3)
    np.testing.assert_array_equal(a1["input_ids"], c1["input_ids"])
    np.testing.assert_array_equal(a1["targets"], c1["targets"])


# ---------------------------------------------------------------- packing --
def _write_varlen_records(root: str, *, files: int = 2,
                          per_file: int = 16) -> None:
    """Documents of varying length (trailing-zero padded to SEQ)."""
    os.makedirs(root, exist_ok=True)
    rng = np.random.default_rng(5)
    for f in range(files):
        path = os.path.join(root, f"mlm-{f:03d}.tfrecord")
        with tf.io.TFRecordWriter(path) as w:
            for _ in range(per_file):
                # Short documents (≤ SEQ/2) so a pack_factor=2 pull
                # actually co-packs multiple docs per row.
                n = int(rng.integers(2, SEQ // 2 + 1))
                ids = np.zeros(SEQ, np.int64)
                ids[:n] = rng.integers(1000, 2000, n)
                w.write(tf.train.Example(features=tf.train.Features(feature={
                    "input_ids": tf.train.Feature(
                        int64_list=tf.train.Int64List(value=ids)),
                })).SerializeToString())


def test_pack_documents_unit():
    from distributed_tensorflow_framework_tpu.data.text_mlm import (
        pack_documents,
    )

    docs = np.zeros((4, 8), np.int32)
    docs[0, :3] = [11, 12, 13]
    docs[1, :4] = [21, 22, 23, 24]
    docs[2, :6] = [31, 32, 33, 34, 35, 36]
    docs[3, :2] = [41, 42]
    packed, segs, leftover = pack_documents(docs, 2, 8)
    assert len(leftover) == 0
    # Row 0: docs 0+1 (3+4=7 tokens, 1 pad); row 1: docs 2+3 (6+2=8).
    np.testing.assert_array_equal(
        packed[0], [11, 12, 13, 21, 22, 23, 24, 0])
    np.testing.assert_array_equal(segs[0], [1, 1, 1, 2, 2, 2, 2, 0])
    np.testing.assert_array_equal(
        packed[1], [31, 32, 33, 34, 35, 36, 41, 42])
    np.testing.assert_array_equal(segs[1], [1, 1, 1, 1, 1, 1, 2, 2])

    # Overflow: same docs into ONE row returns the rest as leftover, in
    # order, so the caller can defer them to the next batch (ADVICE r3).
    _, _, leftover = pack_documents(docs, 1, 8)
    np.testing.assert_array_equal(leftover, docs[2:])


def test_pack_overflow_carries_into_next_batch(tmp_path):
    """Documents that overflow one packed batch's row budget appear at the
    FRONT of the next packed batch — no data loss, and resume replays the
    carry exactly."""
    root = str(tmp_path / "varlen_carry")
    _write_varlen_records(root, files=2, per_file=32)
    # Aggressive pack_factor so overflow happens on most batches.
    cfg = _cfg(root, pack_factor=4)
    ds = make_mlm(cfg, 0, 1, train=True)
    b0 = next(ds)
    snap = ds.state()
    carry = snap.get("carry")
    assert carry, "expected pack_factor=4 to overflow the row budget"
    b1 = next(ds)
    # The first documents of batch 1 are exactly the carried-over docs
    # (stored trimmed to their nonzero prefix, so snapshots stay small).
    first_tokens = np.asarray(carry[0], np.int32)
    n = len(first_tokens)
    assert n and first_tokens.all(), "carry docs must be zero-trimmed"
    seg1 = b1["segment_ids"][0]
    recovered = np.where(b1["targets"][0, :n] >= 0,
                         b1["targets"][0, :n],
                         b1["input_ids"][0, :n])
    np.testing.assert_array_equal(recovered, first_tokens[:n])
    assert (seg1[:n] == 1).all()
    # Restore from the snapshot replays batch 1 bit-exactly (carry rides
    # in the JSON-serializable iterator state).
    import json

    ds2 = make_mlm(cfg, 0, 1, train=True)
    ds2.restore(json.loads(json.dumps(snap)))
    c1 = next(ds2)
    for k in b1:
        np.testing.assert_array_equal(b1[k], c1[k])


def test_packed_mlm_stream_and_resume(tmp_path):
    root = str(tmp_path / "varlen")
    _write_varlen_records(root)
    cfg = _cfg(root, pack_factor=2)
    ds = make_mlm(cfg, 0, 1, train=True)
    b0 = next(ds)
    b1 = next(ds)
    assert set(b0) == {"input_ids", "targets", "attention_mask",
                       "segment_ids"}
    assert b0["segment_ids"].shape == b0["input_ids"].shape
    # Packing packs: some row holds >1 document.
    assert (b0["segment_ids"].max(axis=1) > 1).any()
    # Segments tile contiguously and padding is 0-segmented.
    np.testing.assert_array_equal(
        b0["segment_ids"] > 0, b0["attention_mask"] > 0)
    # Masked positions only at real tokens.
    assert ((b0["targets"] >= 0) <= (b0["attention_mask"] > 0)).all()

    # Fresh pipeline, same seed → identical packed stream.
    ds2 = make_mlm(cfg, 0, 1, train=True)
    c0 = next(ds2)
    for k in b0:
        np.testing.assert_array_equal(b0[k], c0[k])
    # Snapshot-restore replays the SECOND packed batch exactly.
    snap = ds2.state()
    ds3 = make_mlm(cfg, 0, 1, train=True)
    ds3.restore(snap)
    c1 = next(ds3)
    for k in b1:
        np.testing.assert_array_equal(b1[k], c1[k])


def test_packed_eval_stays_unpacked(tmp_path):
    root = str(tmp_path / "varlen_eval")
    _write_varlen_records(root)
    ds = make_mlm(_cfg(root, pack_factor=4), 0, 1, train=False)
    batch = next(ds)
    assert "segment_ids" not in batch


def test_native_rejects_packing(tmp_path):
    root = str(tmp_path / "varlen_nat")
    _write_varlen_records(root)
    with pytest.raises(ValueError, match="pack_factor"):
        make_mlm(_cfg(root, pack_factor=2, use_native_reader=True), 0, 1,
                 train=True)


def test_progression_corpus_tool(tmp_path):
    """scripts/make_progression_mlm.py: the grammar holds (constant
    stride per row, band-bounded) and its records drive the MLM pipeline
    with full exact-eval coverage."""
    import subprocess
    import sys

    out = str(tmp_path / "prog")
    r = subprocess.run(
        [sys.executable, "scripts/make_progression_mlm.py", out,
         "--seq-len", "16", "--train-seqs", "32", "--eval-seqs", "10",
         "--shards", "2"],
        capture_output=True, text=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert r.returncode == 0, r.stderr

    ds = make_mlm(
        _cfg(os.path.join(out, "eval"), vocab_size=2048), 0, 1, train=False)
    assert ds.cardinality == 3  # ceil(10 / 4)
    rows = []
    for b in ds:
        # attention mask covers exactly the non-pad tokens.
        np.testing.assert_array_equal(
            b["attention_mask"], (b["input_ids"] != 0).astype(np.int32))
        for tok, tgt in zip(b["input_ids"], b["targets"]):
            # Reconstruct the original row (unmask via targets).
            orig = np.where(tgt >= 0, tgt, tok)
            if (orig == 0).all():
                continue  # padded row
            rows.append(orig)
    assert len(rows) == 10  # every eval sequence exactly once
    for row in rows:
        assert row.min() >= 1000 and row.max() < 1000 + 499
        d = np.diff(row.astype(np.int64))
        d = np.where(d < 0, d + 499, d)  # band wrap
        assert (d == d[0]).all() and 1 <= d[0] <= 3  # constant stride
