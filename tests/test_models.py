"""Model golden tests: output shapes/dtypes + parameter counts
(SURVEY.md §4 "Unit" row). Golden param counts pin the topologies to their
canonical definitions (ResNet-50 = 25.56M params at 1000 classes)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_framework_tpu.core.config import ModelConfig
from distributed_tensorflow_framework_tpu.models import get_model


def param_count(params) -> int:
    return sum(np.prod(p.shape) for p in jax.tree.leaves(params))


def init_model(config: ModelConfig, input_shape, input_dtype=jnp.float32):
    model = get_model(config)
    rng = jax.random.key(0)
    if config.name == "bert":
        x = jnp.ones(input_shape, jnp.int32)
    else:
        x = jnp.ones(input_shape, input_dtype)
    variables = jax.eval_shape(
        lambda: model.init({"params": rng, "dropout": rng}, x, train=False)
    )
    return model, variables


def test_lenet_shapes_and_params():
    cfg = ModelConfig(name="lenet5", num_classes=10, dtype="float32")
    model = get_model(cfg)
    rng = jax.random.key(0)
    x = jnp.ones((2, 28, 28, 1))
    variables = model.init({"params": rng}, x, train=False)
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (2, 10)
    assert logits.dtype == jnp.float32
    # conv1 6*(5*5*1+1)=156; conv2 16*(5*5*6+1)=2416; fc 400*120+120,
    # 120*84+84, 84*10+10 → 61706 total (classic LeNet-5 with 28x28 input).
    assert param_count(variables["params"]) == 61706


def test_resnet50_param_count():
    cfg = ModelConfig(name="resnet50", num_classes=1000, dtype="bfloat16")
    model, variables = init_model(cfg, (1, 224, 224, 3))
    # Canonical ResNet-50: 25.557M params (incl. BN scale/bias).
    count = param_count(variables["params"])
    assert count == 25_557_032, f"got {count}"


@pytest.mark.slow
def test_resnet50_forward_shape_dtype(devices):
    cfg = ModelConfig(name="resnet50_cifar", num_classes=10, dtype="bfloat16")
    model = get_model(cfg)
    rng = jax.random.key(0)
    x = jnp.ones((4, 32, 32, 3), jnp.float32)
    variables = model.init({"params": rng}, x, train=False)
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (4, 10)
    assert logits.dtype == jnp.float32  # classifier head promotes to fp32
    assert "batch_stats" in variables  # BN present
    # bf16 compute path: stem conv kernel stays fp32 (param_dtype)
    assert variables["params"]["stem"]["conv"]["kernel"].dtype == jnp.float32


def test_fused_qkv_transplant_parity():
    """model.fused_qkv packs the q/k/v projections into one (H, 3H) GEMM.
    Column-block exactness: transplanting an unfused model's weights into
    the fused layout (kernels/biases concatenated along the output axis)
    must reproduce its logits — same math, fewer GEMMs."""
    import numpy as np

    common = dict(name="bert", vocab_size=128, hidden_size=32, num_layers=2,
                  num_heads=2, mlp_dim=64, max_seq_len=16, dtype="float32")
    cfg_sep = ModelConfig(**common)
    cfg_fused = ModelConfig(**common, fused_qkv=True)
    m_sep = get_model(cfg_sep)
    m_fused = get_model(cfg_fused)
    rng = jax.random.key(3)
    ids = jax.random.randint(rng, (2, 16), 0, 128)
    vars_sep = m_sep.init({"params": rng, "dropout": rng}, ids, train=False)
    params = jax.device_get(vars_sep["params"])
    fused_params = {}
    for k, v in params.items():
        if not k.startswith("layer"):
            fused_params[k] = v
            continue
        attn = dict(v["attn"])
        # Fused layout is (H, 3, H) — q/k/v interleaved on the middle axis
        # so TP shards the last axis (parallel/sharding.py qkv rule).
        qkv = {
            "kernel": np.stack(
                [attn["query"]["kernel"], attn["key"]["kernel"],
                 attn["value"]["kernel"]], axis=1),
            "bias": np.stack(
                [attn["query"]["bias"], attn["key"]["bias"],
                 attn["value"]["bias"]], axis=0),
        }
        new_attn = {kk: vv for kk, vv in attn.items()
                    if kk not in ("query", "key", "value")}
        new_attn["qkv"] = qkv
        fused_params[k] = {**v, "attn": new_attn}
    out_sep = m_sep.apply(vars_sep, ids, train=False)
    out_fused = m_fused.apply({"params": fused_params}, ids, train=False)
    np.testing.assert_allclose(np.asarray(out_fused), np.asarray(out_sep),
                               rtol=1e-6, atol=1e-6)


def test_fused_qkv_tp_sharding_rule():
    """The qkv kernel's TP spec must shard the LAST axis (q/k/v stay
    shard-local under tensor parallelism), not the middle stacking axis a
    rank-2 rule would hit."""
    import jax
    from jax.sharding import PartitionSpec as P

    from distributed_tensorflow_framework_tpu.parallel.sharding import (
        TP_RULES, _match_rules,
    )
    from distributed_tensorflow_framework_tpu.core.config import MeshConfig
    from distributed_tensorflow_framework_tpu.core.mesh import create_mesh

    mesh = create_mesh(MeshConfig(data=4, model=2))
    m = mesh.mesh if hasattr(mesh, "mesh") else mesh
    spec = _match_rules("layer0/attn/qkv/kernel", (32, 3, 32), m, TP_RULES)
    assert spec == P(None, None, "model"), spec
    # A flat rank-2 qkv (external models) must fall through to the
    # rank-2 column-parallel rule, not half-apply the rank-3 one.
    spec2 = _match_rules("layer0/attn/qkv/kernel", (32, 96), m, TP_RULES)
    assert spec2 == P(None, "model"), spec2
