"""Model golden tests: output shapes/dtypes + parameter counts
(SURVEY.md §4 "Unit" row). Golden param counts pin the topologies to their
canonical definitions (ResNet-50 = 25.56M params at 1000 classes)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_framework_tpu.core.config import ModelConfig
from distributed_tensorflow_framework_tpu.models import get_model


def param_count(params) -> int:
    return sum(np.prod(p.shape) for p in jax.tree.leaves(params))


def init_model(config: ModelConfig, input_shape, input_dtype=jnp.float32):
    model = get_model(config)
    rng = jax.random.key(0)
    if config.name == "bert":
        x = jnp.ones(input_shape, jnp.int32)
    else:
        x = jnp.ones(input_shape, input_dtype)
    variables = jax.eval_shape(
        lambda: model.init({"params": rng, "dropout": rng}, x, train=False)
    )
    return model, variables


def test_lenet_shapes_and_params():
    cfg = ModelConfig(name="lenet5", num_classes=10, dtype="float32")
    model = get_model(cfg)
    rng = jax.random.key(0)
    x = jnp.ones((2, 28, 28, 1))
    variables = model.init({"params": rng}, x, train=False)
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (2, 10)
    assert logits.dtype == jnp.float32
    # conv1 6*(5*5*1+1)=156; conv2 16*(5*5*6+1)=2416; fc 400*120+120,
    # 120*84+84, 84*10+10 → 61706 total (classic LeNet-5 with 28x28 input).
    assert param_count(variables["params"]) == 61706


def test_resnet50_param_count():
    cfg = ModelConfig(name="resnet50", num_classes=1000, dtype="bfloat16")
    model, variables = init_model(cfg, (1, 224, 224, 3))
    # Canonical ResNet-50: 25.557M params (incl. BN scale/bias).
    count = param_count(variables["params"])
    assert count == 25_557_032, f"got {count}"


@pytest.mark.slow
def test_resnet50_forward_shape_dtype(devices):
    cfg = ModelConfig(name="resnet50_cifar", num_classes=10, dtype="bfloat16")
    model = get_model(cfg)
    rng = jax.random.key(0)
    x = jnp.ones((4, 32, 32, 3), jnp.float32)
    variables = model.init({"params": rng}, x, train=False)
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (4, 10)
    assert logits.dtype == jnp.float32  # classifier head promotes to fp32
    assert "batch_stats" in variables  # BN present
    # bf16 compute path: stem conv kernel stays fp32 (param_dtype)
    assert variables["params"]["stem"]["conv"]["kernel"].dtype == jnp.float32
