"""Golden tests for Inception-v3 and BERT-base (SURVEY.md §4 Unit row)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_framework_tpu.core.config import ModelConfig
from distributed_tensorflow_framework_tpu.models import get_model

# Big-model compile times dominate the suite wall-clock (VERDICT r1 #9).
pytestmark = pytest.mark.slow


def param_count(params) -> int:
    return sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))


def test_inception_v3_shapes_and_params():
    cfg = ModelConfig(name="inception_v3", num_classes=1000, dtype="float32")
    model = get_model(cfg)
    rng = jax.random.key(0)
    x = jnp.ones((1, 299, 299, 3))
    variables = jax.eval_shape(
        lambda: model.init({"params": rng, "dropout": rng}, x, train=False)
    )
    count = param_count(variables["params"])
    # Canonical Inception-v3 with aux head: 27,161,264 params — matches
    # torchvision.models.inception_v3 exactly.
    assert count == 27_161_264, count


@pytest.mark.slowest
def test_inception_v3_forward(devices):
    cfg = ModelConfig(name="inception_v3", num_classes=12, dtype="float32")
    model = get_model(cfg)
    rng = jax.random.key(0)
    x = jnp.ones((2, 96, 96, 3))  # small spatial size for CPU test speed
    variables = model.init({"params": rng, "dropout": rng}, x, train=False)
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (2, 12)
    # Train mode returns main+aux logits.
    out = model.apply(
        variables, x, train=True,
        rngs={"dropout": rng}, mutable=["batch_stats"],
    )[0]
    assert set(out.keys()) == {"logits", "aux_logits"}
    assert out["aux_logits"].shape == (2, 12)


def test_bert_base_param_count():
    cfg = ModelConfig(name="bert", dtype="float32")
    model = get_model(cfg)
    rng = jax.random.key(0)
    ids = jnp.ones((1, 16), jnp.int32)
    variables = jax.eval_shape(
        lambda: model.init({"params": rng, "dropout": rng}, ids, train=False)
    )
    count = param_count(variables["params"])
    # BERT-base with tied MLM head: 110M-ish (109,514,298 canonical for
    # this head layout: 109.48M encoder+embeddings + transform + biases).
    assert 108_000_000 < count < 112_000_000, count


def test_bert_forward(devices):
    cfg = ModelConfig(
        name="bert", vocab_size=1000, hidden_size=64, num_layers=2,
        num_heads=4, mlp_dim=128, max_seq_len=64, dtype="float32",
    )
    model = get_model(cfg)
    rng = jax.random.key(0)
    ids = jnp.ones((2, 32), jnp.int32)
    mask = jnp.ones((2, 32), jnp.int32)
    variables = model.init({"params": rng, "dropout": rng}, ids, mask, train=False)
    logits = model.apply(variables, ids, mask, train=False)
    assert logits.shape == (2, 32, 1000)
    assert logits.dtype == jnp.float32
