"""MoE / expert parallelism (models/moe.py).

Checks routing invariants (balanced-aux value, capacity drops, combine
normalization) and that an expert-parallel BERT trains on an
8-virtual-device mesh with dp+ep(+tp), with expert weights actually
sharded over the expert axis.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_framework_tpu.core.config import load_config
from distributed_tensorflow_framework_tpu.core.mesh import create_mesh
from distributed_tensorflow_framework_tpu.data.infeed import to_global
from distributed_tensorflow_framework_tpu.models.moe import MoEMlp, topk_dispatch
from distributed_tensorflow_framework_tpu.train.step import StepBuilder


def test_topk_dispatch_balanced_aux():
    # Uniform gate logits → perfectly balanced expectation → aux loss 1.0.
    b, s, e = 2, 16, 4
    logits = jnp.zeros((b, s, e), jnp.float32)
    _, _, aux = topk_dispatch(logits, topk=2, capacity=s)
    assert np.isclose(float(aux), 1.0, atol=1e-5)


def test_topk_dispatch_capacity_and_combine():
    rng = np.random.default_rng(0)
    b, s, e, cap = 2, 32, 4, 4
    logits = jnp.asarray(rng.standard_normal((b, s, e)), jnp.float32)
    dispatch, combine, _ = topk_dispatch(logits, topk=2, capacity=cap)
    # Each (expert, slot) holds at most one token.
    per_slot = dispatch.sum(axis=1)  # (B, E, C)
    assert float(per_slot.max()) <= 1.0 + 1e-6
    # Per-token combine weights sum to 1 where dispatched, else 0.
    token_weight = combine.sum(axis=(2, 3))  # (B, S)
    dispatched = dispatch.sum(axis=(2, 3)) > 0
    assert np.allclose(np.asarray(token_weight)[np.asarray(dispatched)], 1.0,
                       atol=1e-5)
    # Tight capacity must actually drop tokens (2*32 slots wanted, 16 avail).
    assert float(dispatch.sum()) <= b * e * cap + 1e-6
    assert bool((~np.asarray(dispatched)).any())


def test_moe_mlp_forward_shape():
    layer = MoEMlp(num_experts=4, mlp_dim=64, dtype=jnp.float32)
    x = jnp.ones((2, 8, 32), jnp.float32)
    vars_ = layer.init(jax.random.key(0), x)
    out, aux = layer.apply(vars_, x)
    assert out.shape == x.shape
    # Aux is an explicit output dict (loss term + diagnostics) — the
    # remat-safe metric contract (models/moe.py).
    assert set(aux) == {"aux_loss", "zloss", "drop_frac"}
    assert np.isfinite(float(aux["aux_loss"]))
    assert vars_["params"]["wi"].shape == (4, 32, 64)
    assert vars_["params"]["wo"].shape == (4, 64, 32)


@pytest.fixture(scope="module")
def moe_cfg():
    return load_config(base={
        "name": "moe-test",
        "mesh": {"data": 2, "expert": 2, "model": 2},
        "model": {
            "name": "bert", "vocab_size": 128, "hidden_size": 32,
            "num_layers": 2, "num_heads": 2, "mlp_dim": 64,
            "max_seq_len": 32, "dtype": "float32",
            "num_experts": 4, "moe_every": 2,
        },
        "data": {"name": "synthetic_mlm", "vocab_size": 128,
                 "global_batch_size": 8, "seq_len": 32},
        "optimizer": {"name": "adamw", "learning_rate": 1e-3},
        "train": {"total_steps": 3},
    })


@pytest.mark.slow
def test_moe_bert_trains_dp_ep_tp(moe_cfg, devices):
    from distributed_tensorflow_framework_tpu.data import get_dataset

    mesh = create_mesh(moe_cfg.mesh)
    builder = StepBuilder(moe_cfg, mesh)
    ds = get_dataset(moe_cfg.data)
    batch = to_global(next(ds), mesh)
    state = builder.init_state(0, batch)

    # Expert weights must be sharded over the expert axis.
    wi = state.params["layer1"]["moe"]["wi"]
    spec = wi.sharding.spec
    assert spec[0] == "expert", f"wi spec {spec}"

    step = builder.make_train_step(batch)
    losses = []
    for _ in range(3):
        state, metrics = step(state, batch)
        m = jax.device_get(metrics)
        assert np.isfinite(float(m["loss"]))
        assert np.isfinite(float(m["moe_aux_loss"]))
        # Router-overflow diagnostic rides the step metrics (mean over
        # MoE layers, in [0, 1]).
        assert 0.0 <= float(m["moe_drop_frac"]) <= 1.0
        losses.append(float(m["loss"]))
    # Eval path strips the aux dict and returns weighted metric sums
    # (exact-eval contract, train/step.py _eval_step).
    eval_step = builder.make_eval_step(batch)
    em = jax.device_get(eval_step(state, batch))
    assert float(em["weight_sum"]) > 0
    assert np.isfinite(float(em["loss_sum"]) / float(em["weight_sum"]))


def test_moe_shard_map_rejected(moe_cfg):
    # Rebuild rather than dataclasses.replace: a shallow copy would share
    # (and mutate) the module-scoped fixture's nested TrainConfig.
    cfg = load_config(base=moe_cfg.to_dict())
    cfg.train.spmd_mode = "shard_map"
    mesh = create_mesh(cfg.mesh)
    with pytest.raises(ValueError, match="expert parallelism"):
        StepBuilder(cfg, mesh)


def test_top1_router_gets_task_gradient():
    """Switch-style top-1 must scale by the RAW gate prob: normalized
    weights are identically 1 and the router would get no task gradient."""
    layer = MoEMlp(num_experts=4, mlp_dim=16, topk=1, dtype=jnp.float32)
    x = jnp.asarray(np.random.default_rng(0).standard_normal((2, 8, 16)),
                    jnp.float32)
    vars_ = layer.init(jax.random.key(0), x)

    def task_loss(params):
        out, _aux = layer.apply({"params": params}, x)
        return (out ** 2).sum()

    g = jax.grad(task_loss)(vars_["params"])
    gate_grad_norm = float(jnp.abs(g["gate"]["kernel"]).sum())
    assert gate_grad_norm > 1e-4, gate_grad_norm


def test_topk_exceeding_experts_rejected():
    logits = jnp.zeros((1, 4, 2), jnp.float32)
    with pytest.raises(ValueError, match="num_experts"):
        topk_dispatch(logits, topk=3, capacity=4)


@pytest.mark.parametrize("topk,cf", [(1, 1.25), (2, 1.25), (2, 0.25),
                                     (1, 0.25), (2, 4.0)])
def test_sorted_dispatch_routing_parity(topk, cf):
    """The sorted dispatcher must reproduce the dense one EXACTLY: same
    token→(expert, slot) table, same combine weights, same aux loss —
    across generous and starved capacities (drops included)."""
    import math

    from distributed_tensorflow_framework_tpu.models.moe import (
        topk_dispatch_sorted,
    )

    rng = np.random.default_rng(1)
    b, s, e = 2, 32, 4
    cap = max(topk, int(math.ceil(topk * s / e * cf)))
    logits = jnp.asarray(rng.standard_normal((b, s, e)), jnp.float32)

    dispatch, combine, aux_d = topk_dispatch(logits, topk, cap)
    (table, tvalid, expert_a, pos_a, comb_w,
     aux_s) = topk_dispatch_sorted(logits, topk, cap)

    # Rebuild the dense one-hots from the sorted index tables.
    disp_s = np.zeros((b, s, e, cap), np.float32)
    bi, ei, ci = np.nonzero(np.asarray(tvalid))
    disp_s[bi, np.asarray(table)[bi, ei, ci], ei, ci] = 1.0
    np.testing.assert_array_equal(disp_s, np.asarray(dispatch))

    comb_s = np.zeros((b, s, e, cap), np.float32)
    for k in range(topk):
        w = np.asarray(comb_w)[:, k]                    # (B, S)
        ex = np.asarray(expert_a)[:, k]
        po = np.asarray(pos_a)[:, k]
        bb, ss = np.nonzero(w > 0)
        comb_s[bb, ss, ex[bb, ss], po[bb, ss]] = w[bb, ss]
    np.testing.assert_allclose(comb_s, np.asarray(combine),
                               rtol=1e-6, atol=1e-6)
    assert np.isclose(float(aux_d), float(aux_s), atol=1e-6)


@pytest.mark.parametrize("topk", [1, 2])
def test_sorted_moe_layer_parity_with_dense(topk):
    """End-to-end layer parity: same params, same input → same output,
    same aux, same drop diagnostic, same parameter GRADIENTS through
    either dispatcher (the sorted path's gathers/scatters must carry the
    identical cotangents the dense einsums do)."""
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.standard_normal((2, 16, 8)), jnp.float32)

    def build(impl):
        return MoEMlp(num_experts=4, mlp_dim=16, topk=topk,
                      capacity_factor=0.75,  # tight → drops in play
                      dtype=jnp.float32, dispatch_impl=impl)

    dense, sorted_ = build("dense"), build("sorted")
    vars_ = dense.init(jax.random.key(0), x)

    out_d, aux_d = dense.apply(vars_, x)
    out_s, aux_s = sorted_.apply(vars_, x)
    np.testing.assert_allclose(np.asarray(out_s), np.asarray(out_d),
                               rtol=1e-5, atol=1e-5)
    assert np.isclose(float(aux_s["aux_loss"]), float(aux_d["aux_loss"]),
                      atol=1e-6)
    assert np.isclose(float(aux_s["drop_frac"]), float(aux_d["drop_frac"]))

    def loss(params, layer):
        out, aux = layer.apply({"params": params}, x)
        return jnp.sum(out ** 2) + 0.01 * aux["aux_loss"]

    g_d = jax.grad(loss)(vars_["params"], dense)
    g_s = jax.grad(loss)(vars_["params"], sorted_)
    for (kp, a), (_, b_) in zip(
            jax.tree_util.tree_leaves_with_path(g_d),
            jax.tree_util.tree_leaves_with_path(g_s)):
        np.testing.assert_allclose(
            np.asarray(b_), np.asarray(a), rtol=2e-4, atol=2e-5,
            err_msg=f"grad mismatch at {kp}")


def test_drop_frac_diagnostic(devices):
    """The router-overflow diagnostic rides the explicit aux dict: zero
    drops at generous capacity, positive at a starved one."""
    from distributed_tensorflow_framework_tpu.models.moe import MoEMlp

    x = jnp.asarray(
        np.random.default_rng(0).standard_normal((2, 16, 8)), jnp.float32)

    def drop_frac(capacity_factor):
        m = MoEMlp(num_experts=4, mlp_dim=16, topk=1,
                   capacity_factor=capacity_factor, dtype=jnp.float32)
        vs = m.init(jax.random.key(0), x)
        out, aux = m.apply(vs, x)
        assert out.shape == x.shape
        return float(aux["drop_frac"])

    assert drop_frac(4.0) == 0.0          # room for every token
    assert drop_frac(0.25) > 0.2          # starved capacity drops plenty


def test_constrain_activation_nop_and_armed(devices):
    """parallel/sharding.constrain_activation: identity without a mesh
    context or when an axis is missing; a real constraint inside one."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from distributed_tensorflow_framework_tpu.parallel.sharding import (
        constrain_activation,
    )

    x = jnp.ones((8, 4))
    # No mesh context → the very same object comes back (not a copy).
    assert constrain_activation(x, "data", None) is x

    mesh = Mesh(np.asarray(devices).reshape(2, 4), ("data", "expert"))
    with mesh:
        # Axis named in the spec but absent from the mesh → no-op.
        assert constrain_activation(x, "model", None) is x

    @jax.jit
    def f(x):
        with mesh:
            return constrain_activation(x * 2, "data", "expert")

    out = f(jax.device_put(x, NamedSharding(mesh, P("data", None))))
    assert out.sharding == NamedSharding(mesh, P("data", "expert"))


def test_router_zloss_knob():
    """ST-MoE router z-loss (round 5): off by default (bit-identical aux),
    on it adds mean(logsumexp(logits)^2) scaled by the relative weight,
    and its gradient SHRINKS router-logit magnitude (the anti-collapse
    mechanism the round-5 forensics motivated)."""
    x = jax.random.normal(jax.random.key(3), (2, 8, 32), jnp.float32)

    base = MoEMlp(num_experts=4, mlp_dim=64, dtype=jnp.float32)
    armed = MoEMlp(num_experts=4, mlp_dim=64, dtype=jnp.float32,
                   zloss_weight=0.1)
    vars_ = base.init(jax.random.key(0), x)

    _, aux_off = base.apply(vars_, x)
    _, aux_on = armed.apply(vars_, x)
    # Same params → the difference IS 0.1 * zloss, and zloss > 0.
    zloss = (float(aux_on["aux_loss"]) - float(aux_off["aux_loss"])) / 0.1
    assert zloss > 0.0
    # The armed layer also reports the raw z term in the aux dict.
    np.testing.assert_allclose(float(aux_on["zloss"]), zloss, rtol=1e-5)
    # Verify against a direct recomputation of the definition.
    gate_k = vars_["params"]["gate"]["kernel"]
    logits = x.astype(jnp.float32) @ gate_k
    expect = float(jnp.mean(jnp.square(
        jax.scipy.special.logsumexp(logits, axis=-1))))
    np.testing.assert_allclose(zloss, expect, rtol=1e-5)

    # The z-loss gradient pushes the gate kernel toward SMALLER logits:
    # scaling the kernel up must increase the aux under the knob.
    big = jax.tree_util.tree_map(lambda t: t, vars_)
    big["params"]["gate"]["kernel"] = gate_k * 3.0
    _, aux_big = armed.apply(big, x)
    _, aux_big_off = base.apply(big, x)
    assert (float(aux_big["aux_loss"])
            - float(aux_big_off["aux_loss"])) > 0.1 * zloss


def test_moe_metrics_survive_remat():
    """moe_drop_frac / moe_zloss must stay observable with model.remat=true:
    they are explicit model outputs threaded through jax.checkpoint, not
    sown intermediates (which die in replayed segments)."""
    from distributed_tensorflow_framework_tpu.models.bert import BertForMLM

    ids = jnp.asarray(
        np.random.default_rng(0).integers(1, 64, (2, 16)), jnp.int32)

    def build(remat):
        return BertForMLM(
            vocab_size=64, hidden_size=16, num_layers=2, num_heads=2,
            mlp_dim=32, max_seq_len=16, dropout_rate=0.0, dtype=jnp.float32,
            num_experts=4, moe_every=2, capacity_factor=0.5,  # forces drops
            moe_zloss_weight=0.1, remat=remat)

    plain, remat = build(False), build(True)
    vars_ = plain.init({"params": jax.random.key(0)}, ids)
    out_p = plain.apply(vars_, ids, train=False)
    out_r = remat.apply(vars_, ids, train=False)

    for key in ("logits", "moe_aux_loss", "moe_drop_frac", "moe_zloss"):
        assert key in out_r, f"{key} missing under remat"
        np.testing.assert_allclose(
            np.asarray(out_r[key]), np.asarray(out_p[key]),
            rtol=1e-6, atol=1e-6, err_msg=f"remat changed {key}")
    # Correctness, not just presence: the starved capacity really drops.
    assert 0.0 < float(out_r["moe_drop_frac"]) <= 1.0
    assert float(out_r["moe_zloss"]) > 0.0

    # Gradients flow identically through the remat'd metric outputs.
    def loss(params, model):
        out = model.apply({"params": params}, ids, train=False)
        from distributed_tensorflow_framework_tpu.train import losses
        return losses.mlm_loss(out["logits"], ids)[0] + 0.01 * out["moe_aux_loss"]

    g_p = jax.grad(loss)(vars_["params"], plain)
    g_r = jax.grad(loss)(vars_["params"], remat)
    for (kp, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(g_p),
            jax.tree_util.tree_leaves_with_path(g_r)):
        np.testing.assert_allclose(
            np.asarray(b), np.asarray(a), rtol=1e-5, atol=1e-6,
            err_msg=f"remat grad mismatch at {kp}")


def test_vocab_mismatch_rejected(moe_cfg):
    """data.vocab_size > model.vocab_size NaNs the MLM loss on step 1
    (out-of-range targets, silent embedding clamp) — StepBuilder must
    reject the pair loudly instead (round-5 NaN forensics)."""
    cfg = load_config(base=moe_cfg.to_dict())
    cfg.data.vocab_size = cfg.model.vocab_size * 2
    mesh = create_mesh(cfg.mesh)
    with pytest.raises(ValueError, match="exceeds model.vocab_size"):
        StepBuilder(cfg, mesh)
