"""Multi-process distributed runtime test (SURVEY.md §4): two OS processes,
coordinator discovery via env vars, 4 global devices, synchronized training.

This is the analogue of the reference's fake-cluster-on-localhost test —
but where the reference needs --ps_hosts/--worker_hosts flags per process,
these workers get identical commands + env and discover each other.
"""

import os
import re
import socket
import subprocess
import sys

import pytest


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_workers(extra_args=()):
    port = _free_port()
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    env["JAX_PLATFORMS"] = ""
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.join(os.path.dirname(__file__),
                                          "distributed_worker.py"),
             str(port), "2", str(i), *extra_args],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env, cwd=os.path.dirname(os.path.dirname(__file__)),
        )
        for i in range(2)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=420)
        outs.append(out)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} failed:\n{out[-3000:]}"
    return outs


@pytest.mark.slow
def test_two_process_training(gang_capability):
    outs = _run_workers()
    losses = []
    for out in outs:
        m = re.search(r"RESULT process=\d+ loss=([0-9.]+)", out)
        assert m, out[-2000:]
        losses.append(float(m.group(1)))
    assert losses[0] == pytest.approx(losses[1], abs=1e-6), losses


@pytest.mark.slow
@pytest.mark.slowest
def test_two_process_exact_eval_uneven_shards(tmp_path, gang_capability):
    """Multi-host exact eval: hosts hold UNEVEN file shards (proc0: 2
    files/8 records, proc1: 1 file/4 records), agree on the padded batch
    count via process_allgather, and must report identical full-set
    metrics covering all 12 records — without deadlocking."""
    pytest.importorskip("tensorflow")

    from conftest import write_imagenet_records

    eval_dir = str(tmp_path / "val")
    write_imagenet_records(eval_dir, split="validation",
                           counts=(5, 4, 3),  # 3 files → stride shards 2/1
                           size=(40, 40),
                           label_fn=lambda n: (n % 10) + 1)

    outs = _run_workers((eval_dir,))
    results = []
    for out in outs:
        m = re.search(r"EVAL process=\d+ examples=(\d+) loss=([0-9.]+)", out)
        assert m, out[-2000:]
        results.append((int(m.group(1)), float(m.group(2))))
    # Full coverage (5+4+3=12 records) and cross-host agreement.
    assert results[0][0] == 12 and results[1][0] == 12, results
    assert results[0][1] == pytest.approx(results[1][1], abs=1e-6), results
