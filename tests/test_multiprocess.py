"""Multi-process distributed runtime test (SURVEY.md §4): two OS processes,
coordinator discovery via env vars, 4 global devices, synchronized training.

This is the analogue of the reference's fake-cluster-on-localhost test —
but where the reference needs --ps_hosts/--worker_hosts flags per process,
these workers get identical commands + env and discover each other.
"""

import os
import re
import socket
import subprocess
import sys

import pytest


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_training():
    port = _free_port()
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    env["JAX_PLATFORMS"] = ""
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.join(os.path.dirname(__file__),
                                          "distributed_worker.py"),
             str(port), "2", str(i)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env, cwd=os.path.dirname(os.path.dirname(__file__)),
        )
        for i in range(2)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=420)
        outs.append(out)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} failed:\n{out[-3000:]}"
    losses = []
    for out in outs:
        m = re.search(r"RESULT process=\d+ loss=([0-9.]+)", out)
        assert m, out[-2000:]
        losses.append(float(m.group(1)))
    assert losses[0] == pytest.approx(losses[1], abs=1e-6), losses
