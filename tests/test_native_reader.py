"""Native C++ TFRecord reader vs TF's own reader (byte- and value-exact)."""

import numpy as np
import pytest


@pytest.fixture(scope="module")
def tfrecord_files(tmp_path_factory):
    import tensorflow as tf

    d = tmp_path_factory.mktemp("records")
    paths = []
    rng = np.random.default_rng(0)
    for shard in range(2):
        p = str(d / f"shard{shard}.tfrecord")
        with tf.io.TFRecordWriter(p) as w:
            for i in range(20):
                seq = rng.integers(0, 1000, 16)
                ex = tf.train.Example(features=tf.train.Features(feature={
                    "input_ids": tf.train.Feature(
                        int64_list=tf.train.Int64List(value=seq.tolist())
                    ),
                    "other": tf.train.Feature(
                        int64_list=tf.train.Int64List(value=[shard, i])
                    ),
                }))
                w.write(ex.SerializeToString())
        paths.append(p)
    return paths


def test_raw_records_match_tf(tfrecord_files):
    import tensorflow as tf

    from distributed_tensorflow_framework_tpu.data.native_reader import (
        NativeRecordReader,
    )

    expected = [r.numpy() for r in tf.data.TFRecordDataset(tfrecord_files)]
    reader = NativeRecordReader(tfrecord_files)
    got = list(reader.records())
    reader.close()
    assert len(got) == len(expected) == 40
    for a, b in zip(got, expected):
        assert a == b


def test_example_parse_matches_tf(tfrecord_files):
    import tensorflow as tf

    from distributed_tensorflow_framework_tpu.data.native_reader import (
        NativeRecordReader,
    )

    ds = tf.data.TFRecordDataset(tfrecord_files).map(
        lambda r: tf.io.parse_single_example(
            r, {"input_ids": tf.io.FixedLenFeature([16], tf.int64)}
        )["input_ids"]
    ).batch(8, drop_remainder=True)
    expected = np.concatenate([b.numpy() for b in ds]).astype(np.int32)

    reader = NativeRecordReader(tfrecord_files)
    got = np.concatenate(list(reader.batches_i32("input_ids", 8, 16)))
    reader.close()
    np.testing.assert_array_equal(got, expected)


@pytest.fixture(scope="module")
def image_record_files(tmp_path_factory):
    import tensorflow as tf

    d = tmp_path_factory.mktemp("img_records")
    rng = np.random.default_rng(7)
    paths, raws, labels = [], [], []
    for shard in range(2):
        p = str(d / f"train-{shard:05d}-of-00002")
        with tf.io.TFRecordWriter(p) as w:
            for i in range(6):
                img = rng.integers(0, 255, (48 + 8 * i, 40, 3), dtype=np.uint8)
                encoded = tf.io.encode_jpeg(img).numpy()
                label = shard * 6 + i + 1
                raws.append(encoded)
                labels.append(label)
                ex = tf.train.Example(features=tf.train.Features(feature={
                    "image/encoded": tf.train.Feature(
                        bytes_list=tf.train.BytesList(value=[encoded])),
                    "image/class/label": tf.train.Feature(
                        int64_list=tf.train.Int64List(value=[label])),
                }))
                w.write(ex.SerializeToString())
        paths.append(p)
    # A validation shard (5 records, deliberately not a batch multiple)
    # for the native exact-eval path.
    vp = str(d / "validation-00000-of-00001")
    with tf.io.TFRecordWriter(vp) as w:
        for i in range(5):
            img = rng.integers(0, 255, (40 + 4 * i, 40, 3), dtype=np.uint8)
            w.write(tf.train.Example(features=tf.train.Features(feature={
                "image/encoded": tf.train.Feature(bytes_list=tf.train.BytesList(
                    value=[tf.io.encode_jpeg(img).numpy()])),
                "image/class/label": tf.train.Feature(
                    int64_list=tf.train.Int64List(value=[i + 1])),
            })).SerializeToString())
    return paths, raws, labels


def test_native_image_decode_matches_tf(image_record_files):
    """C++ JPEG decode + bilinear resize vs TF's decode+resize of the SAME
    records: labels exact, pixels within JPEG-IDCT tolerance."""
    import tensorflow as tf

    from distributed_tensorflow_framework_tpu.data.native_reader import (
        NativeRecordReader,
    )

    paths, raws, labels = image_record_files
    reader = NativeRecordReader(paths)
    batches = list(reader.batches_images(4, 32, 32))
    reader.close()
    assert len(batches) == 3  # 12 records / 4
    got_labels = np.concatenate([lab for _, lab in batches])
    np.testing.assert_array_equal(got_labels, np.asarray(labels, np.int32))
    got_images = np.concatenate([img for img, _ in batches])
    assert got_images.shape == (12, 32, 32, 3)
    assert got_images.min() >= 0.0 and got_images.max() <= 255.0
    for i, raw in enumerate(raws):
        ref = tf.image.resize(
            tf.io.decode_jpeg(raw, channels=3), [32, 32], method="bilinear"
        ).numpy()
        # libjpeg vs TF decoder differ by a few IDCT counts per pixel;
        # resize kernels align on the same corner-aligned bilinear.
        err = np.abs(got_images[i] - ref).mean()
        assert err < 6.0, f"record {i}: mean abs err {err}"


def test_native_imagenet_pipeline_and_resume(image_record_files):
    from distributed_tensorflow_framework_tpu.core.config import DataConfig
    from distributed_tensorflow_framework_tpu.data.imagenet import make_imagenet

    paths, _, _ = image_record_files
    cfg = DataConfig(name="imagenet", data_dir="", global_batch_size=4,
                     image_size=32, use_native_reader=True, seed=3,
                     num_classes=1000)  # fixture labels are 1..n ids
    cfg.data_dir = paths[0].rsplit("/", 1)[0]
    ds = make_imagenet(cfg, 0, 1, train=True)
    a0 = next(ds)
    a1 = next(ds)
    assert a0["image"].shape == (4, 32, 32, 3)
    assert a0["image"].dtype == np.float32
    assert a0["label"].min() >= 0  # [1,N] → [0,N-1]
    # Standardized pixels, not raw [0,255].
    assert abs(float(a0["image"].mean())) < 3.0

    # Snapshot after batch 1, restore into a fresh pipeline → batch 2
    # replays exactly (record shuffle AND flip augmentation included).
    ds2 = make_imagenet(cfg, 0, 1, train=True)
    b0 = next(ds2)
    np.testing.assert_array_equal(a0["image"], b0["image"])
    snap = ds2.state()
    ds3 = make_imagenet(cfg, 0, 1, train=True)
    ds3.restore(snap)
    c1 = next(ds3)
    np.testing.assert_array_equal(a1["image"], c1["image"])
    np.testing.assert_array_equal(a1["label"], c1["label"])

    # Native exact eval: one padded pass over the 5 validation records.
    eval_ds = make_imagenet(cfg, 0, 1, train=False)
    assert eval_ds.cardinality == 2  # ceil(5/4)
    batches = list(eval_ds)
    assert len(batches) == 2
    assert sum(float(b["weight"].sum()) for b in batches) == 5
    labels = np.concatenate([b["label"][b["weight"] > 0] for b in batches])
    assert sorted(labels.tolist()) == [0, 1, 2, 3, 4]  # [1,5] shifted
    # Padded rows are zeroed.
    tail = batches[-1]
    assert (np.asarray(tail["image"], np.float32)[tail["weight"] == 0] == 0).all()


def test_record_shuffle_window(tfrecord_files):
    """Windowed record shuffle: same multiset, shuffled order, seed-
    deterministic, and skip == read-and-discard through the window."""
    from distributed_tensorflow_framework_tpu.data.native_reader import (
        NativeRecordReader,
    )

    def read_all(**kw):
        r = NativeRecordReader(tfrecord_files, **kw)
        out = list(r.records())
        r.close()
        return out

    plain = read_all()
    s7 = read_all(shuffle_window=16, shuffle_seed=7)
    s7b = read_all(shuffle_window=16, shuffle_seed=7)
    s9 = read_all(shuffle_window=16, shuffle_seed=9)
    assert sorted(plain) == sorted(s7) == sorted(s9)  # no loss, no dupes
    assert s7 == s7b          # deterministic given the seed
    assert s7 != plain        # actually shuffled
    assert s7 != s9           # seed matters

    # skip(k) then read == read-and-discard k (the resume contract).
    r = NativeRecordReader(tfrecord_files, shuffle_window=16, shuffle_seed=7)
    assert r.skip_records(7) == 7
    rest = list(r.records())
    r.close()
    assert rest == s7[7:]

    # Skipping past EOF reports the short count instead of hanging.
    r = NativeRecordReader(tfrecord_files, shuffle_window=16, shuffle_seed=7)
    assert r.skip_records(10_000) == 40
    r.close()


def test_crc_detects_corruption(tfrecord_files, tmp_path):
    from distributed_tensorflow_framework_tpu.data.native_reader import (
        NativeRecordReader,
    )

    with open(tfrecord_files[0], "rb") as fh:
        blob = bytearray(fh.read())
    blob[30] ^= 0xFF  # flip a payload byte
    bad = str(tmp_path / "corrupt.tfrecord")
    with open(bad, "wb") as fh:
        fh.write(bytes(blob))
    reader = NativeRecordReader([bad])
    with pytest.raises(RuntimeError, match="crc"):
        list(reader.records())
    reader.close()
