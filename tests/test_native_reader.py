"""Native C++ TFRecord reader vs TF's own reader (byte- and value-exact)."""

import numpy as np
import pytest


@pytest.fixture(scope="module")
def tfrecord_files(tmp_path_factory):
    import tensorflow as tf

    d = tmp_path_factory.mktemp("records")
    paths = []
    rng = np.random.default_rng(0)
    for shard in range(2):
        p = str(d / f"shard{shard}.tfrecord")
        with tf.io.TFRecordWriter(p) as w:
            for i in range(20):
                seq = rng.integers(0, 1000, 16)
                ex = tf.train.Example(features=tf.train.Features(feature={
                    "input_ids": tf.train.Feature(
                        int64_list=tf.train.Int64List(value=seq.tolist())
                    ),
                    "other": tf.train.Feature(
                        int64_list=tf.train.Int64List(value=[shard, i])
                    ),
                }))
                w.write(ex.SerializeToString())
        paths.append(p)
    return paths


def test_raw_records_match_tf(tfrecord_files):
    import tensorflow as tf

    from distributed_tensorflow_framework_tpu.data.native_reader import (
        NativeRecordReader,
    )

    expected = [r.numpy() for r in tf.data.TFRecordDataset(tfrecord_files)]
    reader = NativeRecordReader(tfrecord_files)
    got = list(reader.records())
    reader.close()
    assert len(got) == len(expected) == 40
    for a, b in zip(got, expected):
        assert a == b


def test_example_parse_matches_tf(tfrecord_files):
    import tensorflow as tf

    from distributed_tensorflow_framework_tpu.data.native_reader import (
        NativeRecordReader,
    )

    ds = tf.data.TFRecordDataset(tfrecord_files).map(
        lambda r: tf.io.parse_single_example(
            r, {"input_ids": tf.io.FixedLenFeature([16], tf.int64)}
        )["input_ids"]
    ).batch(8, drop_remainder=True)
    expected = np.concatenate([b.numpy() for b in ds]).astype(np.int32)

    reader = NativeRecordReader(tfrecord_files)
    got = np.concatenate(list(reader.batches_i32("input_ids", 8, 16)))
    reader.close()
    np.testing.assert_array_equal(got, expected)


def test_crc_detects_corruption(tfrecord_files, tmp_path):
    from distributed_tensorflow_framework_tpu.data.native_reader import (
        NativeRecordReader,
    )

    with open(tfrecord_files[0], "rb") as fh:
        blob = bytearray(fh.read())
    blob[30] ^= 0xFF  # flip a payload byte
    bad = str(tmp_path / "corrupt.tfrecord")
    with open(bad, "wb") as fh:
        fh.write(bytes(blob))
    reader = NativeRecordReader([bad])
    with pytest.raises(RuntimeError, match="crc"):
        list(reader.records())
    reader.close()
