"""ISSUE 10 acceptance drills: the goodput ledger + HBM telemetry, live.

Drill 1 — a fault-injected SUPERVISED run (in-process NaN rollback, a
mid-run infeed stall, then a hard SIGKILL with relaunch) must leave an
events trail whose stitched goodput ledger accounts for ~100% of the
measured wall-clock across attempts, restart gap included, and
``scripts/analyze_trace.py`` must print that table (and emit it as one
JSON object under ``--json -``).

Drill 2 — ``python bench.py`` on the CPU backend must report a nonzero
``hbm_peak_bytes_per_chip`` (from the compiled step's memory_analysis —
CPU has no allocator stats) with ``hbm_headroom_frac`` computed against
the capacity table / host-RAM fallback, plus a KIND_MEMORY event in its
telemetry sink.

Tier-2 by their slow marks: real training/bench children, minutes each.
"""

import json
import os
import subprocess
import sys

import pytest

from distributed_tensorflow_framework_tpu.core import goodput, telemetry
from tests.test_fault_tolerance import _child_env
from tests.test_recovery_drills import RECOVERY_DRIVER as OBS_DRIVER

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _obs_driver(ckpt: str, steps: int, overrides: dict[str, str]) -> str:
    extra = "".join(
        f",\n      '--set','{k}={v}'" for k, v in overrides.items())
    return OBS_DRIVER.format(ckpt=ckpt, steps=steps, extra=extra)


def _analyze(args: list[str]) -> subprocess.CompletedProcess:
    return subprocess.run(
        [sys.executable, "scripts/analyze_trace.py", *args],
        env=_child_env({}), cwd=REPO_ROOT, capture_output=True, text=True,
        timeout=120)


@pytest.mark.slow
@pytest.mark.slowest
def test_supervised_faulted_run_goodput_sums_to_wall(tmp_path):
    """Crash + rollback + infeed stall; the ledger must account for it
    all: per-attempt buckets, joined ckpt/rollback/stall counters, and a
    supervisor-classified restart gap — summing to the measured span."""
    ckpt = str(tmp_path / "ckpt")
    prog = _obs_driver(ckpt, steps=80, overrides={
        "resilience.snapshot_interval_steps": "10",
        "resilience.lr_rewarmup_steps": "5",
        "resilience.infeed_deadline_s": "0.5",
        "resilience.infeed_retries": "20",
        "resilience.infeed_backoff_s": "0.1",
        # Emit the ledger/memory samples at every metrics fetch: the
        # SIGKILLed attempt's record is its last periodic snapshot.
        "train.goodput_interval_s": "0",
        "train.memory_interval_s": "0",
    })
    cmd = [sys.executable, "scripts/train_resilient.py",
           "--max-attempts", "3", "--retry-sleep", "0.2", "--jitter", "0",
           "--", sys.executable, "-c", prog]
    r = subprocess.run(
        cmd,
        env=_child_env({
            "DTF_FAULTS":
                "nan_grads:30,stall_infeed:3s:25,crash_at_step:60",
            "DTF_FAULTS_STATE": str(tmp_path / "faults_state.json"),
        }),
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=560)
    assert r.returncode == 0, r.stdout[-3000:] + r.stderr[-3000:]
    assert "exited rc=137" in r.stderr  # the SIGKILL really happened

    ev_path = os.path.join(ckpt, "events.jsonl")
    events = list(telemetry.read_events(ev_path, strict=False))
    kinds = {e["kind"] for e in events}
    assert telemetry.KIND_ROLLBACK in kinds
    assert telemetry.KIND_INFEED_STALL in kinds
    assert telemetry.KIND_GOODPUT in kinds
    assert telemetry.KIND_MEMORY in kinds
    run_ids = {e["run_id"] for e in events}
    assert len(run_ids) == 2  # one ledger per attempt

    mem = [e for e in events if e["kind"] == telemetry.KIND_MEMORY]
    assert all(
        (e.get("metrics") or {}).get("bytes_in_use", 0) > 0
        or (e.get("metrics") or {}).get("peak_bytes_est", 0) > 0
        for e in mem)

    g = goodput.stitch_attempts(ev_path)
    assert g is not None and len(g["attempts"]) == 2
    assert g["counters"]["rollbacks"] >= 1
    assert g["counters"]["infeed_stalls"] >= 1
    assert g["counters"]["ckpt_saves"] >= 1
    # One gap, classified from supervisor_events.jsonl (rc=137 → crash).
    assert len(g["restart_gaps"]) == 1
    assert "crash" in g["restart_gaps"][0]["classification"]
    # THE acceptance invariant: buckets (incl. restart_gap) cover ~100%
    # of the measured wall-clock span across both attempts.
    total = sum(g["buckets"].values())
    assert total == pytest.approx(g["wall_s"], rel=0.02)
    # The faults cost real wall-clock, so they must be visible: most of
    # the 3 s stall sits inside infeed_wait (the prefetch buffer may
    # absorb a slice of it), the rollback bucket is nonzero.
    assert g["buckets"]["infeed_wait"] >= 1.0
    assert g["buckets"].get("rollback", 0) > 0
    assert g["buckets"].get("recompile", 0) > 0  # initial jit + rebuild

    # analyze_trace prints the stitched table for the run directory ...
    a = _analyze([ckpt])
    assert a.returncode == 0, a.stdout + a.stderr
    assert "goodput ledger: 2 attempt(s)" in a.stdout
    assert "restart gap after attempt 1:" in a.stdout
    assert "TOTAL" in a.stdout
    total_line = next(ln for ln in a.stdout.splitlines() if "TOTAL" in ln)
    pct = float(total_line.split()[-1].rstrip("%"))
    assert pct == pytest.approx(100.0, abs=2.0)
    assert "memory:" in a.stdout  # the HBM rollup rendered too

    # ... and --json - emits the whole summary as ONE parseable object.
    j = _analyze([ev_path, "--json", "-"])
    assert j.returncode == 0, j.stdout + j.stderr
    obj = json.loads(j.stdout)
    assert obj["schema"] == "dtf-run-summary/1"
    assert len(obj["goodput_ledger"]["attempts"]) == 2
    assert obj["memory"]["samples"] >= 1


@pytest.mark.slow
@pytest.mark.slowest
def test_bench_cpu_reports_hbm_peak_and_headroom(tmp_path):
    """The bench JSON line must carry nonzero hbm_peak_bytes_per_chip +
    headroom on CPU (memory_analysis ruler, host-RAM capacity fallback),
    and mirror the raw snapshot as a KIND_MEMORY event."""
    sink = str(tmp_path / "bench_events.jsonl")
    r = subprocess.run(
        [sys.executable, "bench.py"],
        env=_child_env({"BENCH_BS": "8", "BENCH_STEPS": "2",
                        "BENCH_WARMUP": "1", "BENCH_JSONL": sink,
                        # This drill pins the CPU backend: _child_env
                        # clears JAX_PLATFORMS for auto-pick, under which
                        # the bench probe hangs hunting for a chip here.
                        "JAX_PLATFORMS": "cpu"}),
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=420)
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    out = json.loads(r.stdout.strip().splitlines()[-1])
    assert out["chip"] == "cpu"
    assert out["hbm_peak_bytes_per_chip"] > 0
    assert out["hbm_peak_source"] == "memory_analysis"
    assert out["hbm_capacity_bytes_per_chip"] > 0
    assert 0.0 < out["hbm_headroom_frac"] <= 1.0

    mem = list(telemetry.read_events(
        sink, kind=telemetry.KIND_MEMORY, strict=True))
    assert len(mem) == 1
    assert mem[0]["extra"]["source"] == "bench"
    assert (mem[0]["extra"]["hbm_peak_bytes_per_chip"]
            == out["hbm_peak_bytes_per_chip"])
    assert mem[0]["extra"]["analysis"]["peak_bytes_est"] > 0
