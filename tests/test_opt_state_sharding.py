"""ZeRO-1 optimizer-state sharding (optimizer.shard_opt_state).

SURVEY.md §7 hard part 5 / PAPERS.md cross-replica weight-update sharding:
params stay replicated (pure-DP reference semantics) while Adam/momentum
slots shard over the fsdp mesh axis. Asserts the spec layout AND that the
parameter trajectory matches plain replicated DP.
"""

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from distributed_tensorflow_framework_tpu.core.config import load_config
from distributed_tensorflow_framework_tpu.core.mesh import create_mesh
from distributed_tensorflow_framework_tpu.data.infeed import to_global
from distributed_tensorflow_framework_tpu.train.step import StepBuilder


def _cfg(mesh_axes, shard_opt: bool):
    return load_config(base={
        "name": "zero1",
        "mesh": mesh_axes,
        "model": {"name": "lenet5", "num_classes": 10, "dtype": "float32"},
        "data": {"name": "synthetic_images", "global_batch_size": 64,
                 "image_size": 28, "channels": 1},
        "optimizer": {"name": "adam", "learning_rate": 0.01,
                      "shard_opt_state": shard_opt},
        "train": {"total_steps": 5, "log_interval": 5},
    })


def _batch(mesh):
    rng = np.random.default_rng(0)
    host = {
        "image": rng.standard_normal((64, 28, 28, 1)).astype(np.float32),
        "label": rng.integers(0, 10, 64).astype(np.int32),
    }
    return to_global(host, mesh)


def _run(mesh_axes, shard_opt: bool, steps: int = 5):
    cfg = _cfg(mesh_axes, shard_opt)
    mesh = create_mesh(cfg.mesh)
    builder = StepBuilder(cfg, mesh)
    batch = _batch(mesh)
    state = builder.init_state(0, batch)
    step = builder.make_train_step(batch)
    for _ in range(steps):
        state, metrics = step(state, batch)
    return builder, state, float(jax.device_get(metrics["loss"]))


def test_specs_params_replicated_opt_sharded(devices):
    cfg = _cfg({"data": 4, "fsdp": 2}, True)
    mesh = create_mesh(cfg.mesh)
    builder = StepBuilder(cfg, mesh)
    specs = builder.state_specs(_batch(mesh))
    # Params and EMA replicated — the reference's DP layout.
    for leaf in jax.tree.leaves(specs.params, is_leaf=lambda x: isinstance(x, P)):
        assert leaf == P(), leaf
    # Adam mu/nu slots sharded over fsdp wherever a dim divides.
    opt_specs = [
        s for s in jax.tree.leaves(
            specs.opt_state, is_leaf=lambda x: isinstance(x, P))
        if s != P()
    ]
    assert opt_specs, "no optimizer-state leaf got sharded"
    assert any("fsdp" in s for s in opt_specs)


def test_zero1_memory_is_sharded(devices):
    _, state, _ = _run({"data": 4, "fsdp": 2}, True, steps=1)
    # Find a sharded mu slot: its per-device shard must be half the global.
    found = False
    for leaf in jax.tree.leaves(state.opt_state):
        if hasattr(leaf, "sharding") and leaf.ndim >= 1 and leaf.size > 1:
            spec = leaf.sharding.spec
            if any(s == "fsdp" for s in spec if s):
                shard = leaf.addressable_shards[0].data
                assert shard.size == leaf.size // 2
                found = True
    assert found, "no fsdp-sharded optimizer slot materialized"
    # Params stay replicated: every device holds the full array.
    for leaf in jax.tree.leaves(state.params):
        assert leaf.addressable_shards[0].data.size == leaf.size


@pytest.mark.slow
def test_zero1_trajectory_matches_pure_dp(devices):
    _, s_dp, loss_dp = _run({"data": 8}, False)
    _, s_z1, loss_z1 = _run({"data": 4, "fsdp": 2}, True)
    assert np.isfinite(loss_z1)
    np.testing.assert_allclose(loss_dp, loss_z1, rtol=1e-5)
    # Different mesh shapes reduce gradients in different orders; Adam's
    # eps-division amplifies that float noise slightly, so the tolerance
    # is loose enough for reduction-order drift but far below any layout
    # bug. atol covers the square-kernel case: pick_fsdp_dim's
    # deterministic trailing-dim tie-break shards a different dim than
    # the old scan-order pick, shifting reduction order (observed worst
    # case one element in 48k at 5.3e-5 absolute after 5 steps; a layout
    # bug shows up orders of magnitude above that on most elements).
    for a, b in zip(jax.tree.leaves(jax.device_get(s_dp.params)),
                    jax.tree.leaves(jax.device_get(s_z1.params))):
        np.testing.assert_allclose(a, b, rtol=5e-4, atol=1e-4)


def test_shard_opt_state_rejected_without_fsdp_axis(devices):
    cfg = _cfg({"data": 8}, True)
    mesh = create_mesh(cfg.mesh)
    with pytest.raises(ValueError, match="fsdp"):
        StepBuilder(cfg, mesh)


def test_shard_opt_state_rejected_under_shard_map(devices):
    cfg = _cfg({"data": 4, "fsdp": 2}, True)
    cfg.train.spmd_mode = "shard_map"
    mesh = create_mesh(cfg.mesh)
    with pytest.raises(ValueError, match="jit"):
        StepBuilder(cfg, mesh)
