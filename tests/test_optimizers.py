"""Optimizer construction (train/optimizers.py).

The reference wraps base optimizers in SyncReplicasOptimizer; here the
base update rule itself must match the optax primitives it claims to wrap
(schedule-equivalence, VERDICT r1 item 8 for RMSProp).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from distributed_tensorflow_framework_tpu.core.config import OptimizerConfig
from distributed_tensorflow_framework_tpu.train.optimizers import make_optimizer


def _trajectory(tx, params, grads_seq):
    opt_state = tx.init(params)
    out = []
    for g in grads_seq:
        updates, opt_state = tx.update(g, opt_state, params)
        params = optax.apply_updates(params, updates)
        out.append(jax.device_get(params))
    return out


def test_rmsprop_matches_optax_primitive():
    cfg = OptimizerConfig(
        name="rmsprop", learning_rate=0.045, rms_decay=0.9,
        momentum=0.9, eps=1.0, schedule="constant",
    )
    tx, sched = make_optimizer(cfg, total_steps=10)
    # initial_scale=1.0 matches TF1 RMSPropOptimizer's ones-initialized
    # mean-square slot — the production choice (train/optimizers.py).
    ref = optax.rmsprop(0.045, decay=0.9, eps=1.0, momentum=0.9,
                        initial_scale=1.0)

    params = {"w": jnp.array([1.0, -2.0, 3.0]), "b": jnp.array(0.5)}
    rng = np.random.default_rng(0)
    grads_seq = [
        {"w": jnp.asarray(rng.standard_normal(3), jnp.float32),
         "b": jnp.asarray(rng.standard_normal(), jnp.float32)}
        for _ in range(5)
    ]
    ours = _trajectory(tx, params, grads_seq)
    theirs = _trajectory(ref, params, grads_seq)
    for a, b in zip(ours, theirs):
        for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
            np.testing.assert_allclose(x, y, rtol=1e-6, atol=1e-7)
    assert float(sched(0)) == 0.045


def test_rmsprop_no_momentum():
    cfg = OptimizerConfig(name="rmsprop", learning_rate=0.01, momentum=0.0)
    tx, _ = make_optimizer(cfg, total_steps=10)
    params = {"w": jnp.ones(4)}
    g = {"w": jnp.full((4,), 0.5)}
    updates, _ = tx.update(g, tx.init(params), params)
    ref = optax.rmsprop(0.01, decay=0.9, eps=1e-8, initial_scale=1.0)
    ref_updates, _ = ref.update(g, ref.init(params), params)
    np.testing.assert_allclose(updates["w"], ref_updates["w"], rtol=1e-6)
