"""Packed-sequence (segment-aware) attention across all three impls.

Sequence packing concatenates documents into one row; attention must be
block-diagonal over the segment ids, equivalent to running each document
through attention separately. The reference here does exactly that —
slices each segment out and attends it alone — so the xla, pallas and
ring implementations are all checked against an independent construction,
not against each other.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_framework_tpu.models.bert import (
    dot_product_attention,
)

B, S, H, D = 2, 256, 2, 32
# Segment layout per row (crosses the 32-token ring-chunk boundaries and
# differs per batch row; 0 marks padding).
SEGS = np.zeros((B, S), np.int32)
SEGS[0, :100] = 1
SEGS[0, 100:180] = 2
SEGS[0, 180:230] = 3
SEGS[1, :130] = 1
SEGS[1, 130:256] = 2


def _qkv(key):
    kq, kk, kv = jax.random.split(key, 3)
    shape = (B, S, H, D)
    return (jax.random.normal(kq, shape, jnp.float32),
            jax.random.normal(kk, shape, jnp.float32),
            jax.random.normal(kv, shape, jnp.float32))


def _per_segment_reference(q, k, v, segs):
    """Attend each segment separately and scatter back — the definition
    of packing correctness. Padding (seg 0) rows attend among themselves;
    their outputs are irrelevant (zero-weighted downstream) but computed
    the same way for comparison."""
    out = np.zeros(q.shape, np.float32)
    for b in range(q.shape[0]):
        for seg in np.unique(segs[b]):
            idx = np.where(segs[b] == seg)[0]
            o = dot_product_attention(
                jnp.asarray(q[b:b + 1, idx]), jnp.asarray(k[b:b + 1, idx]),
                jnp.asarray(v[b:b + 1, idx]))
            out[b, idx] = np.asarray(o)[0]
    return out


@pytest.fixture(scope="module")
def data():
    q, k, v = _qkv(jax.random.key(0))
    ref = _per_segment_reference(np.asarray(q), np.asarray(k),
                                 np.asarray(v), SEGS)
    return q, k, v, jnp.asarray(SEGS), ref


def test_xla_segmented_matches_per_segment(data):
    q, k, v, segs, ref = data
    out = dot_product_attention(q, k, v, segment_ids=segs)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-5, atol=2e-5)


def test_flash_segmented_matches_per_segment(data):
    from distributed_tensorflow_framework_tpu.ops.flash_attention import (
        flash_attention,
    )

    q, k, v, segs, ref = data
    out = flash_attention(q, k, v, segment_ids=segs)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-5, atol=2e-5)


def test_flash_segmented_gradients_match_xla(data):
    from distributed_tensorflow_framework_tpu.ops.flash_attention import (
        flash_attention,
    )

    q, k, v, segs, ref = data
    # Weight the loss by real-token positions so padding rows (whose
    # outputs legitimately differ in no way that matters) drop out.
    w = jnp.asarray((SEGS > 0).astype(np.float32))[..., None, None]

    def loss(attn_fn):
        def f(q, k, v):
            out = attn_fn(q, k, v).astype(jnp.float32)
            return jnp.sum(jnp.sin(out) * w)
        return f

    g_flash = jax.grad(
        loss(lambda q, k, v: flash_attention(q, k, v, segment_ids=segs)),
        argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(
        loss(lambda q, k, v: dot_product_attention(
            q, k, v, segment_ids=segs)), argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4, err_msg=f"d{name}")


@pytest.mark.parametrize("chunk_impl", ["xla", "flash"])
def test_ring_segmented_matches_per_segment(devices, monkeypatch,
                                            chunk_impl, data):
    """Segments cross ring-shard boundaries; the segment shard rotates
    with its K/V chunk, so the block-diagonal mask stays correct all the
    way around the ring — for both per-chunk implementations."""
    from distributed_tensorflow_framework_tpu.core.config import MeshConfig
    from distributed_tensorflow_framework_tpu.core.mesh import create_mesh
    from distributed_tensorflow_framework_tpu.parallel import ring
    from distributed_tensorflow_framework_tpu.parallel.ring import (
        ring_attention_sharded,
    )

    monkeypatch.setattr(
        ring, "FLASH_CHUNK_MIN", 0 if chunk_impl == "flash" else 10**9)
    mesh = create_mesh(MeshConfig(data=1, seq=8))
    q, k, v, segs, ref = data
    out = jax.jit(lambda q, k, v: ring_attention_sharded(
        q, k, v, mesh=mesh, segment_ids=segs))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-5, atol=2e-5)


def test_bert_accepts_segment_ids(devices):
    """End-to-end: the model forward with packing differs from unpacked
    (the mask bites) and matches the xla impl across attention impls."""
    from distributed_tensorflow_framework_tpu.core.config import ModelConfig
    from distributed_tensorflow_framework_tpu.models import get_model

    cfg = dict(vocab_size=128, hidden_size=32, num_layers=2, num_heads=2,
               mlp_dim=64, max_seq_len=64, dtype="float32", dropout_rate=0.0)
    ids = jnp.asarray(
        np.random.default_rng(0).integers(1, 128, (2, 64)), jnp.int32)
    mask = jnp.ones((2, 64), jnp.int32)
    segs = jnp.asarray(
        np.repeat([[1, 2, 3, 4]], 16, axis=0).T.reshape(1, 64).repeat(2, 0))

    outs = {}
    for impl in ("xla", "pallas"):
        m = get_model(ModelConfig(name="bert", attention_impl=impl, **cfg))
        vs = m.init(jax.random.key(1), ids, mask, train=False)
        packed = m.apply(vs, ids, mask, segs, train=False)
        unpacked = m.apply(vs, ids, mask, train=False)
        assert not np.allclose(np.asarray(packed), np.asarray(unpacked))
        outs[impl] = np.asarray(packed)
    np.testing.assert_allclose(outs["pallas"], outs["xla"],
                               rtol=2e-4, atol=2e-4)


def test_packed_train_step_end_to_end(devices):
    """StepBuilder feeds segment_ids through to the model when the batch
    carries them (data.pack_factor>1 path): one train step runs and the
    loss is finite on an 8-replica mesh."""
    import jax as _jax

    from distributed_tensorflow_framework_tpu.core.config import load_config
    from distributed_tensorflow_framework_tpu.core.mesh import create_mesh
    from distributed_tensorflow_framework_tpu.data.infeed import to_global
    from distributed_tensorflow_framework_tpu.train.step import StepBuilder

    cfg = load_config(base={
        "name": "packed-step",
        "mesh": {"data": 8},
        "model": {"name": "bert", "vocab_size": 512, "hidden_size": 32,
                  "num_layers": 1, "num_heads": 2, "mlp_dim": 64,
                  "max_seq_len": 32, "dtype": "float32",
                  "attention_impl": "pallas"},
        # data.vocab_size must not exceed the model's — StepBuilder now
        # rejects the mismatch (the default-30522 stream would feed token
        # ids the 512-entry embedding clamps silently).
        "data": {"name": "synthetic_mlm", "vocab_size": 512,
                 "global_batch_size": 8, "seq_len": 32},
        "optimizer": {"name": "adamw", "learning_rate": 1e-3},
        "train": {"total_steps": 1},
    })
    mesh = create_mesh(cfg.mesh)
    builder = StepBuilder(cfg, mesh)
    rng = np.random.default_rng(0)
    tokens = rng.integers(200, 500, (8, 32)).astype(np.int32)
    tokens[:, 20:] = 0  # padding tail
    segs = np.zeros((8, 32), np.int32)
    segs[:, :8] = 1
    segs[:, 8:20] = 2
    targets = np.where(rng.random((8, 32)) < 0.15, tokens, -1).astype(np.int32)
    targets[:, 20:] = -1
    host = {
        "input_ids": tokens,
        "targets": targets,
        "attention_mask": (tokens != 0).astype(np.int32),
        "segment_ids": segs,
    }
    batch = to_global(host, mesh)
    state = builder.init_state(0, batch)
    step = builder.make_train_step(batch)
    state, metrics = step(state, batch)
    assert np.isfinite(float(_jax.device_get(metrics["loss"])))


def test_xla_segmented_bf16_no_nan():
    """Regression: fully-masked pad-query rows under bf16 scores must not
    NaN (f32-min rounds to -inf in bf16; masking now happens in f32)."""
    q, k, v, segs, _ = (None,) * 5
    rng = np.random.default_rng(3)
    q = jnp.asarray(rng.standard_normal((2, 32, 2, 16)), jnp.bfloat16)
    segs = np.zeros((2, 32), np.int32)
    segs[:, :20] = 1  # tail 12 positions are padding (segment 0)
    out = dot_product_attention(q, q, q, segment_ids=jnp.asarray(segs),
                                dtype=jnp.bfloat16)
    assert np.isfinite(np.asarray(out, np.float32)).all()
    # Combined with a key mask (the production packed-batch shape).
    mask = jnp.asarray((segs > 0))[:, None, None, :]
    out = dot_product_attention(q, q, q, mask=mask,
                                segment_ids=jnp.asarray(segs),
                                dtype=jnp.bfloat16)
    assert np.isfinite(np.asarray(out, np.float32)).all()


def test_packed_positions_reset_per_segment(devices):
    """A document packed at row offset c must see pos_embedding[0..len) —
    the model forward over a packed row equals the forward over each
    document in its own (unpacked) row."""
    from distributed_tensorflow_framework_tpu.core.config import ModelConfig
    from distributed_tensorflow_framework_tpu.models import get_model

    cfg = dict(vocab_size=128, hidden_size=32, num_layers=1, num_heads=2,
               mlp_dim=64, max_seq_len=32, dtype="float32", dropout_rate=0.0)
    rng = np.random.default_rng(9)
    doc_a = rng.integers(1, 128, 12).astype(np.int32)
    doc_b = rng.integers(1, 128, 20).astype(np.int32)

    packed = np.concatenate([doc_a, doc_b])[None, :]          # (1, 32)
    segs = np.concatenate([np.full(12, 1), np.full(20, 2)])[None, :]
    mask_packed = np.ones((1, 32), np.int32)

    # Unpacked: each doc alone in a zero-padded row.
    rows = np.zeros((2, 32), np.int32)
    rows[0, :12] = doc_a
    rows[1, :20] = doc_b
    mask_rows = (rows != 0).astype(np.int32)

    m = get_model(ModelConfig(name="bert", attention_impl="xla", **cfg))
    vs = m.init(jax.random.key(0), jnp.asarray(packed),
                jnp.asarray(mask_packed), train=False)
    out_packed = np.asarray(m.apply(
        vs, jnp.asarray(packed), jnp.asarray(mask_packed),
        jnp.asarray(segs), train=False))
    out_rows = np.asarray(m.apply(
        vs, jnp.asarray(rows), jnp.asarray(mask_rows), train=False))

    np.testing.assert_allclose(out_packed[0, :12], out_rows[0, :12],
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(out_packed[0, 12:], out_rows[1, :20],
                               rtol=1e-5, atol=1e-5)
