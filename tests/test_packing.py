"""Sequence packing primitives + goodput-per-padded-token telemetry.

data/packing.py (moved out of text_mlm so any tokenized reader can pack):
deterministic first-fit document packing, the real/padded token census
that rides the iterator state, and the KIND_DATA_PACKING rollup the
Trainer emits from it (packing_efficiency — the number packing exists to
raise). The end-to-end packed-stream resume lives in
tests/test_mlm_pipeline.py and tests/test_data_state.py.
"""

import numpy as np

from distributed_tensorflow_framework_tpu.core import telemetry
from distributed_tensorflow_framework_tpu.data import packing


def _doc(*lens, s=12):
    rows = np.zeros((len(lens), s), np.int32)
    for i, n in enumerate(lens):
        rows[i, :n] = np.arange(1, n + 1) + 100 * i
    return rows


def test_pack_documents_lays_docs_end_to_end_with_segment_ids():
    packed, segs, leftover = packing.pack_documents(_doc(5, 4, 3), 1, 12)
    assert leftover.size == 0
    assert np.count_nonzero(packed[0]) == 12
    # Three documents, numbered 1..3 in order; no padding positions left.
    assert segs[0].tolist() == [1] * 5 + [2] * 4 + [3] * 3


def test_pack_documents_returns_overflow_in_order():
    packed, segs, leftover = packing.pack_documents(_doc(7, 7, 7), 1, 12)
    # Doc 1 fills row 0 to 7; doc 2 doesn't fit the remaining 5 columns,
    # the row budget is exhausted → docs 2 and 3 come back, in order.
    assert np.count_nonzero(packed[0]) == 7
    assert len(leftover) == 2
    np.testing.assert_array_equal(leftover, _doc(7, 7, 7)[1:])


def test_pack_documents_skips_empty_rows():
    rows = _doc(4, 0, 3)
    packed, segs, leftover = packing.pack_documents(rows, 1, 12)
    assert leftover.size == 0
    assert segs[0, :7].tolist() == [1] * 4 + [2] * 3


def test_token_census_counters_accumulate_in_state():
    state = {}
    batch = _doc(5, 3)          # 8 real, 16 padded positions over (2, 12)
    packing.accumulate_counters(state, batch)
    assert state[packing.REAL_TOKENS_KEY] == 8
    assert state[packing.PADDED_TOKENS_KEY] == 16
    packing.accumulate_counters(state, batch)
    assert state[packing.REAL_TOKENS_KEY] == 16  # cumulative census


def test_packing_stats_rollup():
    stats = packing.packing_stats(75, 25)
    assert stats == {"real_tokens": 75, "padded_tokens": 25,
                     "total_tokens": 100, "packing_efficiency": 0.75}
    assert packing.packing_stats(0, 0)["packing_efficiency"] is None


def test_kind_data_packing_event_and_summary_rollup(tmp_path):
    """KIND_DATA_PACKING end to end: emitted metrics survive the event
    log and surface in both summarize_events and format_run_summary."""
    path = str(tmp_path / "events.jsonl")
    w = telemetry.TelemetryWriter(path, run_id="pack-test")
    w.emit(telemetry.KIND_DATA_PACKING, step=4,
           metrics=packing.packing_stats(600, 200))
    w.emit(telemetry.KIND_DATA_PACKING, step=8,
           metrics=packing.packing_stats(1500, 500))  # cumulative: last wins
    w.close()

    summary = telemetry.summarize_events(path)
    pack = summary["data"]["packing"]
    assert pack["real_tokens"] == 1500 and pack["padded_tokens"] == 500
    assert pack["packing_efficiency"] == 0.75

    text = telemetry.format_run_summary(summary)
    assert "packing: 1,500 real / 500 padded tokens" in text, text
    assert "efficiency 0.750" in text
