"""Pipeline parallelism (parallel/pipeline.py).

The load-bearing check is numerics: the circular GPipe schedule over the
``pipe`` axis must produce bit-comparable logits AND gradients to a plain
sequential apply of the same stacked params. Then an end-to-end dp+pp
training step via StepBuilder, and the config validation surface.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_framework_tpu.core.config import load_config
from distributed_tensorflow_framework_tpu.core.mesh import create_mesh
from distributed_tensorflow_framework_tpu.data.infeed import to_global
from distributed_tensorflow_framework_tpu.train.step import StepBuilder


def _make_model(mesh, stages=4, microbatches=4):
    from distributed_tensorflow_framework_tpu.parallel.pipeline import PipelinedBert

    return PipelinedBert(
        vocab_size=64, hidden_size=32, num_layers=4, num_heads=2,
        mlp_dim=64, max_seq_len=16, dropout_rate=0.0, dtype=jnp.float32,
        mesh=mesh, num_stages=stages, num_microbatches=microbatches,
    )


@pytest.fixture(scope="module")
def pp_mesh(devices):
    from distributed_tensorflow_framework_tpu.core.config import MeshConfig

    return create_mesh(MeshConfig(data=2, pipe=4))


def test_pipeline_matches_reference(pp_mesh):
    model = _make_model(pp_mesh)
    ids = jnp.asarray(
        np.random.default_rng(0).integers(1, 64, (8, 16)), jnp.int32
    )
    variables = model.init({"params": jax.random.key(0)}, ids)

    @jax.jit
    def pipelined(v, ids):
        return model.apply(v, ids, train=False)

    ref = model.apply_reference(variables, ids, train=False)
    out = pipelined(variables, ids)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.slow
def test_pipeline_gradients_match_reference(pp_mesh):
    model = _make_model(pp_mesh)
    rng = np.random.default_rng(1)
    ids = jnp.asarray(rng.integers(1, 64, (8, 16)), jnp.int32)
    tgt = jnp.asarray(
        np.where(rng.random((8, 16)) < 0.3, ids, -1), jnp.int32
    )
    variables = model.init({"params": jax.random.key(0)}, ids)

    from distributed_tensorflow_framework_tpu.train import losses

    def loss_pipe(params):
        logits = model.apply({"params": params}, ids, train=False)
        return losses.mlm_loss(logits, tgt)[0]

    def loss_ref(params):
        logits = model.apply_reference({"params": params}, ids, train=False)
        return losses.mlm_loss(logits, tgt)[0]

    g_pipe = jax.jit(jax.grad(loss_pipe))(variables["params"])
    g_ref = jax.grad(loss_ref)(variables["params"])
    flat_p, _ = jax.flatten_util.ravel_pytree(g_pipe)
    flat_r, _ = jax.flatten_util.ravel_pytree(g_ref)
    np.testing.assert_allclose(np.asarray(flat_p), np.asarray(flat_r),
                               rtol=2e-4, atol=1e-6)


def _pp_cfg(stages=4, microbatches=0, **model_extra):
    model = {
        "name": "bert", "vocab_size": 64, "hidden_size": 32,
        "num_layers": 4, "num_heads": 2, "mlp_dim": 64,
        "max_seq_len": 16, "dtype": "float32", "dropout_rate": 0.1,
        "pipeline_stages": stages, "pipeline_microbatches": microbatches,
    }
    model.update(model_extra)
    return load_config(base={
        "name": "pp-test",
        "mesh": {"data": 2, "pipe": 4},
        "model": model,
        "data": {"name": "synthetic_mlm", "vocab_size": 64,
                 "global_batch_size": 16, "seq_len": 16},
        "optimizer": {"name": "adamw", "learning_rate": 1e-3},
        "train": {"total_steps": 3},
    })


@pytest.mark.slow
def test_pipeline_trains_dp_pp(pp_mesh):
    from distributed_tensorflow_framework_tpu.data import get_dataset

    cfg = _pp_cfg()
    builder = StepBuilder(cfg, pp_mesh)
    ds = get_dataset(cfg.data)
    batch = to_global(next(ds), pp_mesh)
    state = builder.init_state(0, batch)

    # Stacked layer params must be sharded over pipe on dim 0.
    leaf = jax.tree.leaves(state.params["pipeline_layers"])[0]
    assert leaf.sharding.spec[0] == "pipe", leaf.sharding.spec

    step = builder.make_train_step(batch)
    prev = None
    for _ in range(3):
        state, metrics = step(state, batch)
        m = jax.device_get(metrics)
        assert np.isfinite(float(m["loss"]))
        prev = float(m["loss"])
    assert prev is not None
    # GPipe schedule bubble is logged per step: S=4 stages, M=4
    # microbatches (defaulted from stages) → (S-1)/(M+S-1) = 3/7.
    assert abs(float(m["pipe_bubble_frac"]) - 3.0 / 7.0) < 1e-6
    eval_step = builder.make_eval_step(batch)
    em = jax.device_get(eval_step(state, batch))
    assert float(em["weight_sum"]) > 0
    assert np.isfinite(float(em["loss_sum"]) / float(em["weight_sum"]))


def test_pipeline_validation(pp_mesh, devices):
    # stages must equal mesh pipe size
    with pytest.raises(ValueError, match="must equal"):
        StepBuilder(_pp_cfg(stages=2), pp_mesh)
    # ring attention cannot nest inside the pipeline shard_map
    with pytest.raises(ValueError, match="ring"):
        StepBuilder(_pp_cfg(attention_impl="ring"), pp_mesh)
    # non-transformer models cannot pipeline
    cfg = _pp_cfg()
    cfg.model.name = "lenet5"
    with pytest.raises(ValueError, match="only wired"):
        StepBuilder(cfg, pp_mesh)
