"""Pipeline parallelism (parallel/pipeline.py + parallel/schedule.py).

The load-bearing check is numerics: every schedule (gpipe, 1f1b,
interleaved) over the ``pipe`` axis must produce matching logits AND
gradients against a plain sequential apply of the same stacked params —
on a composed dp+pp mesh AND an fsdp+pp mesh. Then the static slot-table
algebra, the schedule-dispatch surface, an end-to-end dp+pp training
step via StepBuilder, and the config validation surface.

Grad-comparison rule: compare PER LEAF via np.asarray. On this jax
version, eager ``jnp.concatenate`` over P("pipe")-sharded leaves on a
mesh with replicated data axes (i.e. ``ravel_pytree`` of the grad tree)
mis-reshards and returns values scaled by the data-axis size — a
measurement artifact that once masqueraded as a 2x gradient bug.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_framework_tpu.core.config import load_config
from distributed_tensorflow_framework_tpu.core.mesh import create_mesh
from distributed_tensorflow_framework_tpu.data.infeed import to_global
from distributed_tensorflow_framework_tpu.parallel import schedule as sched
from distributed_tensorflow_framework_tpu.train.step import StepBuilder

# (schedule, virtual_stages) triples every parity test runs. v=0 means
# "resolve the default" (1 for gpipe/1f1b; layers/stages for interleaved,
# here 8/4 = 2).
SCHEDULE_CASES = [("gpipe", 0), ("1f1b", 0), ("interleaved", 2)]


def _make_model(mesh, stages=4, microbatches=4, layers=4,
                schedule="gpipe", virtual_stages=0):
    from distributed_tensorflow_framework_tpu.parallel.pipeline import PipelinedBert

    return PipelinedBert(
        vocab_size=64, hidden_size=32, num_layers=layers, num_heads=2,
        mlp_dim=64, max_seq_len=16, dropout_rate=0.0, dtype=jnp.float32,
        mesh=mesh, num_stages=stages, num_microbatches=microbatches,
        schedule=schedule, virtual_stages=virtual_stages,
    )


def _leaf_maxerr(a, b):
    """Max |a-b| over the tree, leaf-wise in host memory (see module
    docstring for why NOT ravel_pytree)."""
    errs = jax.tree.map(
        lambda x, y: float(np.max(np.abs(np.asarray(x) - np.asarray(y)))),
        a, b)
    return max(jax.tree.leaves(errs))


@pytest.fixture(scope="module")
def pp_mesh(devices):
    from distributed_tensorflow_framework_tpu.core.config import MeshConfig

    return create_mesh(MeshConfig(data=2, pipe=4))


@pytest.fixture(scope="module")
def pp_problem(pp_mesh):
    """Shared L=8 problem: inputs, params, reference logits and reference
    gradients (computed once per module, reused by every schedule case)."""
    rng = np.random.default_rng(1)
    ids = jnp.asarray(rng.integers(1, 64, (16, 16)), jnp.int32)
    tgt = jnp.asarray(np.where(rng.random((16, 16)) < 0.3, ids, -1),
                      jnp.int32)
    model = _make_model(pp_mesh, microbatches=8, layers=8)
    variables = model.init({"params": jax.random.key(0)}, ids)
    ref_logits = model.apply_reference(variables, ids, train=False)

    from distributed_tensorflow_framework_tpu.train import losses

    def loss_ref(params):
        logits = model.apply_reference({"params": params}, ids, train=False)
        return losses.mlm_loss(logits, tgt)[0]

    g_ref = jax.tree.map(np.asarray, jax.jit(jax.grad(loss_ref))(
        variables["params"]))
    return {"ids": ids, "tgt": tgt, "variables": variables,
            "ref_logits": np.asarray(ref_logits), "g_ref": g_ref}


def test_pipeline_matches_reference(pp_mesh):
    model = _make_model(pp_mesh)
    ids = jnp.asarray(
        np.random.default_rng(0).integers(1, 64, (8, 16)), jnp.int32
    )
    variables = model.init({"params": jax.random.key(0)}, ids)

    @jax.jit
    def pipelined(v, ids):
        return model.apply(v, ids, train=False)

    ref = model.apply_reference(variables, ids, train=False)
    out = pipelined(variables, ids)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("schedule,v", SCHEDULE_CASES)
def test_schedule_parity_logits_and_grads(pp_mesh, pp_problem, schedule, v):
    """Every schedule pins logits AND per-leaf gradient parity against
    the sequential reference on the dp=2 x pipe=4 mesh. Tier-1 on
    purpose: the seed's grad-parity check was slow-marked, which is how a
    (suspected) dp+pp gradient bug went unexamined for several rounds."""
    ids, tgt = pp_problem["ids"], pp_problem["tgt"]
    variables = pp_problem["variables"]
    model = _make_model(pp_mesh, microbatches=8, layers=8,
                        schedule=schedule, virtual_stages=v)

    out = jax.jit(lambda vv: model.apply(vv, ids, train=False))(variables)
    np.testing.assert_allclose(np.asarray(out), pp_problem["ref_logits"],
                               rtol=1e-5, atol=1e-5)

    from distributed_tensorflow_framework_tpu.train import losses

    def loss_pipe(params):
        logits = model.apply({"params": params}, ids, train=False)
        return losses.mlm_loss(logits, tgt)[0]

    g = jax.jit(jax.grad(loss_pipe))(variables["params"])
    assert _leaf_maxerr(g, pp_problem["g_ref"]) < 2e-4


def test_fsdp_pipe_parity(devices):
    """PP composes with FSDP: {fsdp:2, pipe:4} logits and per-leaf grads
    match the sequential reference (the batch shards over the fsdp axis
    via batch_spec; the stacked layer dim shards over pipe)."""
    from distributed_tensorflow_framework_tpu.core.config import MeshConfig

    mesh = create_mesh(MeshConfig(fsdp=2, pipe=4))
    rng = np.random.default_rng(3)
    ids = jnp.asarray(rng.integers(1, 64, (8, 16)), jnp.int32)
    tgt = jnp.asarray(np.where(rng.random((8, 16)) < 0.3, ids, -1),
                      jnp.int32)
    model = _make_model(mesh, microbatches=4, layers=4, schedule="1f1b")
    variables = model.init({"params": jax.random.key(0)}, ids)

    ref = model.apply_reference(variables, ids, train=False)
    out = jax.jit(lambda vv: model.apply(vv, ids, train=False))(variables)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)

    from distributed_tensorflow_framework_tpu.train import losses

    def loss(params, fn):
        logits = fn({"params": params}, ids, train=False)
        return losses.mlm_loss(logits, tgt)[0]

    g = jax.jit(jax.grad(lambda p: loss(p, model.apply)))(
        variables["params"])
    g_ref = jax.jit(jax.grad(lambda p: loss(p, model.apply_reference)))(
        variables["params"])
    assert _leaf_maxerr(g, g_ref) < 2e-4


# ---------------------------------------------------------------------------
# Static schedule algebra (parallel/schedule.py) — pure Python, no mesh.
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("s,m", [(2, 2), (2, 8), (4, 4), (4, 8), (8, 8)])
def test_1f1b_slot_table(s, m):
    table = sched.slot_table("1f1b", s, m)
    # combined fwd+bwd table: per-direction num_slots + S-1 drain slots
    assert len(table) == m + 2 * s - 2 == sched.num_slots("1f1b", s, m) + s - 1
    fwd_seen, bwd_seen = set(), set()
    for slot in table:
        assert slot.kind in ("warmup", "steady", "cooldown")
        for st, mb in slot.fwd.items():
            # stage st runs forward for microbatch mb at slot t = st + mb
            assert slot.t == st + mb
            fwd_seen.add((st, mb))
        for st, mb in slot.bwd.items():
            # backward for mb on stage st fires 2(S-1)-2*st slots after
            # its forward wavefront: t = mb + 2(S-1) - st
            assert slot.t == mb + 2 * (s - 1) - st
            bwd_seen.add((st, mb))
    # every (stage, microbatch) pair appears exactly once each direction
    want = {(st, mb) for st in range(s) for mb in range(m)}
    assert fwd_seen == want
    assert bwd_seen == want
    # steady state = 1F1B proper: slots where some stage does both
    steady = [sl for sl in table if sl.fwd and sl.bwd]
    assert all(sl.kind == "steady" for sl in steady)
    assert table[0].kind == "warmup" and table[-1].kind == "cooldown"


@pytest.mark.parametrize("s,m,v", [(2, 2, 1), (4, 8, 1), (4, 8, 2), (2, 4, 4)])
def test_forward_slot_tables_cover_all_chunks(s, m, v):
    from collections import Counter

    for name in ("gpipe", "interleaved"):
        vv = v if name == "interleaved" else 1
        table = sched.slot_table(name, s, m, vv)
        assert len(table) == sched.num_slots(name, s, m, vv)
        seen = Counter()
        for slot in table:
            assert not slot.bwd  # forward-only; autodiff mirrors it
            for st, mb in slot.fwd.items():
                seen[(st, mb)] += 1
        # every stage touches every microbatch exactly v times (once per
        # virtual chunk it hosts)
        assert seen == {(st, mb): vv for st in range(s)
                        for mb in range(m)}


def test_bubble_fractions():
    # GPipe and 1F1B share the same bubble (1F1B wins on memory, not
    # bubble); interleaving divides the warmup/cooldown ramp by v.
    assert sched.bubble_frac("gpipe", 4, 8) == pytest.approx(3 / 11)
    assert sched.bubble_frac("1f1b", 4, 8) == pytest.approx(3 / 11)
    assert sched.bubble_frac("interleaved", 4, 8, 2) == pytest.approx(3 / 19)
    # ISSUE acceptance: at equal stages/microbatches the interleaved
    # bubble is strictly below the recorded dp+pp artifact's 0.2727.
    assert sched.bubble_frac("interleaved", 4, 8, 2) < 0.2727
    assert (sched.bubble_frac("interleaved", 4, 8, 2)
            < sched.bubble_frac("gpipe", 4, 8))
    # more microbatches monotonically shrinks the bubble
    assert (sched.bubble_frac("gpipe", 4, 16)
            < sched.bubble_frac("gpipe", 4, 8))


def test_1f1b_activation_residency_is_o_stages():
    # The whole point of 1F1B: in-flight activations cap at min(M, 2S-1)
    # — independent of microbatch count — where GPipe grows with M.
    for m in (8, 16, 64, 256):
        assert sched.peak_inflight("1f1b", 4, m) == min(m, 2 * 4 - 1) == 7
        assert sched.peak_inflight("gpipe", 4, m) == m + 3
    assert sched.peak_inflight("1f1b", 8, 256) == 15  # still O(S)
    # cross-check against the slot table at the worst stage (0): a
    # microbatch's stage-input activation lives from its stage-0 forward
    # slot until its stage-0 backward slot
    for s, m in [(2, 8), (4, 8), (4, 32)]:
        live = peak = 0
        for slot in sched.slot_table("1f1b", s, m):
            live += 0 in slot.fwd   # stage-0 fwd stores the activation
            peak = max(peak, live)
            live -= 0 in slot.bwd   # stage-0 bwd consumes it
        assert peak == sched.peak_inflight("1f1b", s, m)


def test_resolve_virtual_validation():
    assert sched.resolve_virtual("gpipe", 4, 8, 0, 8) == 1
    assert sched.resolve_virtual("interleaved", 4, 8, 0, 8) == 2
    assert sched.resolve_virtual("interleaved", 4, 8, 2, 16) == 2
    with pytest.raises(ValueError, match="divisible"):
        sched.resolve_virtual("interleaved", 4, 6, 0, 8)  # M % S != 0
    with pytest.raises(ValueError, match="divisible"):
        sched.resolve_virtual("interleaved", 4, 8, 3, 8)  # L % (S*v) != 0
    with pytest.raises(ValueError, match="virtual_stages"):
        sched.resolve_virtual("gpipe", 4, 8, 2, 8)  # v>1 needs interleaved
    with pytest.raises(ValueError, match="schedule"):
        sched.resolve_virtual("zigzag", 4, 8, 0, 8)


def test_schedule_dispatch(pp_mesh, monkeypatch):
    """pipeline_apply routes each schedule name to its executor."""
    from distributed_tensorflow_framework_tpu.parallel import pipeline as pl

    calls = []
    real_circ, real_inter = pl._circular_fwd_fn, pl._interleaved_fwd_fn
    real_1f1b = pl._pipeline_apply_1f1b
    monkeypatch.setattr(pl, "_circular_fwd_fn",
                        lambda *a, **k: calls.append("gpipe")
                        or real_circ(*a, **k))
    monkeypatch.setattr(pl, "_interleaved_fwd_fn",
                        lambda *a, **k: calls.append("interleaved")
                        or real_inter(*a, **k))
    monkeypatch.setattr(pl, "_pipeline_apply_1f1b",
                        lambda *a, **k: calls.append("1f1b")
                        or real_1f1b(*a, **k))

    ids = jnp.asarray(np.random.default_rng(0).integers(1, 64, (16, 16)),
                      jnp.int32)
    for schedule, v in SCHEDULE_CASES:
        calls.clear()
        model = _make_model(pp_mesh, microbatches=8, layers=8,
                            schedule=schedule, virtual_stages=v)
        variables = model.init({"params": jax.random.key(0)}, ids)
        model.apply(variables, ids, train=False)
        assert schedule in calls, (schedule, calls)


def _pp_cfg(stages=4, microbatches=0, **model_extra):
    model = {
        "name": "bert", "vocab_size": 64, "hidden_size": 32,
        "num_layers": 4, "num_heads": 2, "mlp_dim": 64,
        "max_seq_len": 16, "dtype": "float32", "dropout_rate": 0.1,
        "pipeline_stages": stages, "pipeline_microbatches": microbatches,
    }
    model.update(model_extra)
    return load_config(base={
        "name": "pp-test",
        "mesh": {"data": 2, "pipe": 4},
        "model": model,
        "data": {"name": "synthetic_mlm", "vocab_size": 64,
                 "global_batch_size": 16, "seq_len": 16},
        "optimizer": {"name": "adamw", "learning_rate": 1e-3},
        "train": {"total_steps": 3},
    })


@pytest.mark.slow
def test_pipeline_trains_dp_pp(pp_mesh):
    from distributed_tensorflow_framework_tpu.data import get_dataset

    cfg = _pp_cfg()
    builder = StepBuilder(cfg, pp_mesh)
    ds = get_dataset(cfg.data)
    batch = to_global(next(ds), pp_mesh)
    state = builder.init_state(0, batch)

    # Stacked layer params must be sharded over pipe on dim 0.
    leaf = jax.tree.leaves(state.params["pipeline_layers"])[0]
    assert leaf.sharding.spec[0] == "pipe", leaf.sharding.spec

    step = builder.make_train_step(batch)
    prev = None
    for _ in range(3):
        state, metrics = step(state, batch)
        m = jax.device_get(metrics)
        assert np.isfinite(float(m["loss"]))
        prev = float(m["loss"])
    assert prev is not None
    # GPipe schedule bubble is logged per step: S=4 stages, M=4
    # microbatches (defaulted from stages) → (S-1)/(M+S-1) = 3/7.
    assert abs(float(m["pipe_bubble_frac"]) - 3.0 / 7.0) < 1e-6
    eval_step = builder.make_eval_step(batch)
    em = jax.device_get(eval_step(state, batch))
    assert float(em["weight_sum"]) > 0
    assert np.isfinite(float(em["loss_sum"]) / float(em["weight_sum"]))


@pytest.mark.slow
@pytest.mark.parametrize("schedule,v,bubble", [
    ("1f1b", 0, 3 / 11),
    ("interleaved", 2, 3 / 19),
])
def test_pipeline_trains_dp_pp_schedules(pp_mesh, schedule, v, bubble):
    """End-to-end dp+pp StepBuilder training under the non-default
    schedules; the logged analytic bubble must match schedule.py and the
    interleaved one must beat the recorded GPipe artifact (0.2727)."""
    from distributed_tensorflow_framework_tpu.data import get_dataset

    cfg = _pp_cfg(microbatches=8, num_layers=8,
                  pipeline_schedule=schedule, pipeline_virtual_stages=v)
    builder = StepBuilder(cfg, pp_mesh)
    ds = get_dataset(cfg.data)
    batch = to_global(next(ds), pp_mesh)
    state = builder.init_state(0, batch)
    step = builder.make_train_step(batch)
    for _ in range(2):
        state, metrics = step(state, batch)
    m = jax.device_get(metrics)
    assert np.isfinite(float(m["loss"]))
    assert abs(float(m["pipe_bubble_frac"]) - bubble) < 1e-6
    if schedule == "interleaved":
        # beats the recorded dp+pp GPipe artifact bubble (3/11 = 0.2727)
        assert float(m["pipe_bubble_frac"]) < 0.2727


def test_pipeline_validation(pp_mesh, devices):
    # stages must equal mesh pipe size
    with pytest.raises(ValueError, match="must equal"):
        StepBuilder(_pp_cfg(stages=2), pp_mesh)
    # ring attention cannot nest inside the pipeline shard_map
    with pytest.raises(ValueError, match="ring"):
        StepBuilder(_pp_cfg(attention_impl="ring"), pp_mesh)
    # non-transformer models cannot pipeline
    cfg = _pp_cfg()
    cfg.model.name = "lenet5"
    with pytest.raises(ValueError, match="only wired"):
        StepBuilder(cfg, pp_mesh)
