"""core/platform.enable_compilation_cache: the persistent-cache knob.

Config-plumbing only — no compiles are run with the cache armed, and the
previous jax.config value is always restored, because on the CPU test
backend a persistent cache poisons later pallas interpret-mode tests
(reloaded executables embed dead host-callback pointers; see pytest.ini).
"""

import jax

from distributed_tensorflow_framework_tpu.core.platform import (
    enable_compilation_cache,
)


def test_empty_dir_is_off():
    assert enable_compilation_cache("") is False


def test_enable_points_jax_at_the_dir(tmp_path):
    cache_dir = str(tmp_path / "xla_cache")
    before = jax.config.jax_compilation_cache_dir
    try:
        assert enable_compilation_cache(cache_dir) is True
        assert jax.config.jax_compilation_cache_dir == cache_dir
        import os

        assert os.path.isdir(cache_dir)  # created eagerly
    finally:
        jax.config.update("jax_compilation_cache_dir", before)
