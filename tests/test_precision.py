"""The precision policy layer + fused donated optimizer update (ISSUE 13).

Four contracts, CPU-verifiable (the throughput claims live on the §13
chip ladder, scripts/chip_window_queue.sh):

  * bf16 policy parity — ``precision.activation_dtype=bf16`` over an f32
    model config tracks the f32 run's loss within a pinned tolerance for
    3 steps, and the master params stay f32 the whole way;
  * fused-update bit-parity — ``precision.fused_update=true`` (the optax
    apply moved inside parallel/zero.fused_update_walk's bucketed walk)
    reproduces the unfused ZeRO path's params BITWISE at f32, because
    the per-bucket optax chains are positional subsets of the whole-tree
    chain (per-leaf update rules);
  * int8 block-codec matmul error — models/layers.quantized_matmul stays
    inside the EQuARX-style two-operand bound, 2·maxabs/254 per scaled
    product block;
  * checkpoint round-trip — a bf16-policy run saves f32 masters, and a
    policy-free restore reads them back unchanged: checkpoints are
    precision-policy independent (docs/MIGRATING.md).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distributed_tensorflow_framework_tpu.core.config import load_config
from distributed_tensorflow_framework_tpu.core.mesh import create_mesh
from distributed_tensorflow_framework_tpu.train.step import StepBuilder


def _cfg(**precision):
    base = {
        "name": "precision-test",
        "model": {"name": "lenet5", "num_classes": 10, "dtype": "float32"},
        "data": {"name": "synthetic_images", "global_batch_size": 64,
                 "image_size": 28, "channels": 1},
        "optimizer": {"name": "sgd_momentum", "learning_rate": 0.05,
                      "weight_decay": 1e-4,
                      "zero_sharding": "shard_map"},
        "train": {"total_steps": 3, "spmd_mode": "shard_map", "seed": 0},
        "mesh": {"data": 8},
        "precision": precision,
    }
    return load_config(base=base)


def _run_steps(cfg, steps=3):
    mesh = create_mesh(cfg.mesh)
    sb = StepBuilder(cfg, mesh)
    rng = np.random.default_rng(0)
    batch = {
        "image": jnp.asarray(
            rng.standard_normal((64, 28, 28, 1)), jnp.float32),
        "label": jnp.asarray(rng.integers(0, 10, 64), jnp.int32),
    }
    state = sb.init_state(0, batch)
    step = sb.make_train_step(batch)
    losses = []
    for _ in range(steps):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    return state, losses


@pytest.fixture(scope="module")
def f32_run(devices):
    """The shared f32 control arm: both parity tests compare against the
    same 3-step run (one compile instead of two keeps tier-1 lean)."""
    return _run_steps(_cfg())


# ------------------------------------------------------- bf16 policy parity --
def test_bf16_policy_tracks_f32_loss_and_keeps_f32_masters(devices, f32_run):
    _, f32_losses = f32_run
    state, bf16_losses = _run_steps(_cfg(activation_dtype="bf16"))
    # Pinned tolerance: bf16 rounding perturbs each matmul by ~2^-8
    # relative; over a 3-step LeNet run the loss trajectories stay within
    # a few e-3 of each other (measured ~7e-4 max on the seed run).
    np.testing.assert_allclose(bf16_losses, f32_losses, atol=5e-3)
    for leaf in jax.tree.leaves(state.params):
        assert leaf.dtype == jnp.float32, "bf16 policy touched the masters"


# --------------------------------------------------- fused-update bit-parity --
def test_fused_update_is_bitwise_equal_to_unfused_zero(devices, f32_run):
    unfused, ul = f32_run
    fused, fl = _run_steps(_cfg(fused_update=True))
    assert ul == fl
    for a, b in zip(jax.tree.leaves(unfused.params),
                    jax.tree.leaves(fused.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # The fused opt_state is a tuple of per-bucket states — same bytes,
    # regrouped; flattening both must give bitwise-identical slot leaves
    # (order may differ between the monolithic and per-bucket trees, so
    # compare as sorted multisets of byte strings).
    def slot_bytes(state):
        return sorted(np.asarray(leaf).tobytes()
                      for leaf in jax.tree.leaves(state.opt_state)
                      if hasattr(leaf, "dtype"))
    assert slot_bytes(unfused) == slot_bytes(fused)


def test_fused_update_requires_zero_sharding(devices):
    base = {
        "model": {"name": "lenet5"},
        "data": {"name": "synthetic_images", "global_batch_size": 64,
                 "image_size": 28, "channels": 1},
        "optimizer": {"name": "sgd_momentum", "learning_rate": 0.05},
        "train": {"spmd_mode": "shard_map"},
        "mesh": {"data": 8},
        "precision": {"fused_update": True},
    }
    with pytest.raises(ValueError, match="fused_update"):
        load_config(base=base)


# ------------------------------------------------------ int8 matmul codec --
def test_quantized_matmul_error_bound(devices):
    """Block-scaled int8 x @ w vs the f32 product: each output element
    sums nb block products, each off by at most one rounding per operand
    — maxabs_x/254 relative on x times the w magnitude and vice versa.
    The per-block bound below is the conservative product form."""
    from distributed_tensorflow_framework_tpu.models.layers import (
        quantized_matmul,
    )

    rng = np.random.default_rng(3)
    x = (rng.standard_normal((32, 500)) *
         np.logspace(-1, 1, 32)[:, None]).astype(np.float32)
    w = rng.standard_normal((500, 24)).astype(np.float32)
    block = 64
    exact = x @ w
    got = np.asarray(quantized_matmul(jnp.asarray(x), jnp.asarray(w),
                                      block_size=block))
    # Per-block error: |dx|<=bx/254 over the block of x (bx = block max),
    # |dw|<=bw/254; the cross terms bound each block's contribution by
    # (bx·|w| + bw·|x| + bx·bw/254)·block/254. Sum over blocks, take the
    # worst output element.
    nb = -(-x.shape[1] // block)
    xp = np.pad(x, ((0, 0), (0, nb * block - x.shape[1])))
    wp = np.pad(w, ((0, nb * block - w.shape[0]), (0, 0)))
    xb = xp.reshape(x.shape[0], nb, block)
    wb = wp.reshape(nb, block, w.shape[1])
    bx = np.abs(xb).max(axis=2)                      # (M, nb)
    bw = np.abs(wb).max(axis=1)                      # (nb, N)
    cross = (np.einsum("mb,bn->mn", bx, np.abs(wb).sum(axis=1))
             + np.einsum("mbk,bn->mn", np.abs(xb), bw)
             + block * np.einsum("mb,bn->mn", bx, bw) / 254) / 254
    err = np.abs(got - exact)
    assert (err <= cross + 1e-5).all(), float((err - cross).max())
    # And the headline sanity: ~1% relative error on random data.
    rel = err.max() / np.abs(exact).max()
    assert rel < 0.05, rel


def test_quant_dense_matches_dense_params_and_shapes(devices):
    """QuantDense owns the same param names/shapes as nn.Dense, so an
    int8-matmul config restores f32 checkpoints taken without it."""
    import flax.linen as nn

    from distributed_tensorflow_framework_tpu.models.layers import QuantDense

    x = jnp.asarray(np.random.default_rng(0).standard_normal((4, 12)),
                    jnp.float32)
    qd = QuantDense(features=7)
    d = nn.Dense(features=7)
    qv = qd.init(jax.random.PRNGKey(0), x)
    dv = d.init(jax.random.PRNGKey(0), x)
    q_shapes = jax.tree.map(lambda l: (l.shape, str(l.dtype)), qv)
    d_shapes = jax.tree.map(lambda l: (l.shape, str(l.dtype)), dv)
    assert q_shapes == d_shapes
    # Gradients flow (straight-through on the rounded values).
    g = jax.grad(lambda v: jnp.sum(qd.apply(v, x) ** 2))(qv)
    assert all(np.isfinite(np.asarray(l)).all() for l in jax.tree.leaves(g))


def test_int8_matmul_policy_trains(devices):
    """precision.matmul_dtype=int8 end to end on the lenet step: loss is
    finite and params stay f32 (the codec quantizes activations/weights
    on the fly, never the stored masters)."""
    state, losses = _run_steps(
        _cfg(activation_dtype="bf16", matmul_dtype="int8"), steps=2)
    assert all(np.isfinite(losses))
    for leaf in jax.tree.leaves(state.params):
        assert leaf.dtype == jnp.float32


# --------------------------------------------------------- ckpt round-trip --
def test_checkpoints_are_precision_policy_independent(devices, tmp_path):
    """Train under the bf16 policy + fused update, checkpoint, then
    restore WITHOUT any precision block: masters are f32 on disk and
    bit-identical after the round trip (docs/MIGRATING.md)."""
    from distributed_tensorflow_framework_tpu.train import Trainer

    base = {
        "name": "precision-ckpt",
        "model": {"name": "lenet5", "num_classes": 10, "dtype": "float32"},
        "data": {"name": "synthetic_images", "global_batch_size": 64,
                 "image_size": 28, "channels": 1},
        "optimizer": {"name": "sgd_momentum", "learning_rate": 0.05},
        "train": {"total_steps": 4, "spmd_mode": "shard_map", "seed": 0},
        "mesh": {"data": 8},
        "checkpoint": {"directory": str(tmp_path / "ckpt"),
                       "save_interval_steps": 4, "async_save": False},
        "precision": {"activation_dtype": "bf16"},
    }
    cfg = load_config(base=base)
    trainer = Trainer(cfg)
    trainer.train()
    saved = jax.tree.map(np.asarray, trainer.state.params)
    for leaf in jax.tree.leaves(saved):
        assert leaf.dtype == np.float32

    plain = load_config(base={**base, "precision": {}})
    restored = Trainer(plain)
    restored.build()
    assert int(jax.device_get(restored.state.step)) == 4
    for a, b in zip(jax.tree.leaves(saved),
                    jax.tree.leaves(
                        jax.tree.map(np.asarray, restored.state.params))):
        np.testing.assert_array_equal(a, b)
