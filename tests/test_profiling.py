"""Tracing/profiling subsystem (core/profiling.py + ProfileHook).

SURVEY.md §5 "Tracing / profiling": XPlane traces + step annotations +
host-side phase timing. These were dead surface in round 1 — now the
Trainer reports ``time_*_ms`` phases every log interval and ProfileHook
captures a real trace (both asserted here).
"""

import glob
import os

import numpy as np

from distributed_tensorflow_framework_tpu.core.config import load_config
from distributed_tensorflow_framework_tpu.core.profiling import StepTimer
from distributed_tensorflow_framework_tpu.train import Trainer


def _cfg(**train_overrides):
    base = {
        "name": "prof-test",
        "mesh": {"data": 8},
        "model": {"name": "lenet5", "num_classes": 10, "dtype": "float32"},
        "data": {"name": "synthetic_images", "global_batch_size": 64,
                 "image_size": 28, "channels": 1},
        "optimizer": {"name": "sgd_momentum", "learning_rate": 0.05},
        "train": dict({"total_steps": 6, "log_interval": 3}, **train_overrides),
    }
    return load_config(base=base)


def test_step_timer_phases():
    t = StepTimer()
    with t.phase("a"):
        pass
    with t.phase("a"):
        pass
    with t.phase("b"):
        pass
    means = t.means()
    assert set(means) == {"time_a_ms", "time_b_ms"}
    assert all(v >= 0 for v in means.values())
    t.reset()
    assert t.means() == {}


def test_trainer_reports_phase_times(devices):
    trainer = Trainer(_cfg())
    metrics = trainer.train()
    for key in ("time_infeed_ms", "time_dispatch_ms", "time_metrics_fetch_ms"):
        assert key in metrics, sorted(metrics)
        assert np.isfinite(metrics[key]) and metrics[key] >= 0


def test_profile_hook_captures_trace(devices, tmp_path):
    cfg = _cfg(profile_start=2, profile_stop=4)
    cfg.checkpoint.directory = str(tmp_path / "run")
    cfg.checkpoint.save_interval_steps = 1000
    trainer = Trainer(cfg)
    trainer.train()
    # An XPlane trace landed under <ckpt_dir>/traces.
    produced = glob.glob(
        os.path.join(str(tmp_path / "run"), "traces", "**", "*.xplane.pb"),
        recursive=True,
    )
    assert produced, "ProfileHook produced no XPlane trace"
