"""End-to-end recovery-ladder drills (docs/RESILIENCE.md).

Real training children under DTF_FAULTS, proving the ladder's three
acceptance contracts: (1) a transient NaN is detected, rolled back, and
skipped IN PROCESS — the run finishes rc=0 with no relaunch; (2) a
stalled input pipeline surfaces through the infeed watchdog and the loop
retries through it; (3) a persistent anomaly (re-poisoned data region)
exhausts max_rollbacks and escalates with the distinct
ANOMALY_ESCALATION_RC, which the supervisor classifies as
persistent_anomaly without feeding the crash-loop breaker.

The fast per-rung mechanics live in tests/test_anomaly.py /
tests/test_infeed.py / tests/test_faults.py; these are tier-2 by their
slow marks (subprocess training children, minutes each).
"""

import os
import subprocess
import sys

import pytest

from distributed_tensorflow_framework_tpu.core import supervision, telemetry
from tests.test_fault_tolerance import _child_env

RECOVERY_DRIVER = """
import sys
import jax; jax.config.update('jax_platforms','cpu')
from distributed_tensorflow_framework_tpu.cli.train import main
sys.exit(
 main(['--set','model.name=lenet5','--set','model.dtype=float32',
      '--set','data.name=synthetic_images','--set','data.image_size=28',
      '--set','data.channels=1','--set','data.global_batch_size=64',
      '--set','mesh.data=8',
      '--set','optimizer.name=sgd_momentum','--set','optimizer.learning_rate=0.01',
      '--set','train.total_steps={steps}','--set','train.log_interval=10',
      '--set','train.eval_steps=0',
      '--set','checkpoint.directory={ckpt}',
      '--set','checkpoint.save_interval_steps=20',
      '--set','checkpoint.async_save=false'{extra}]))
"""


def _driver(ckpt: str, steps: int, overrides: dict[str, str]) -> str:
    extra = "".join(f",\n      '--set','{k}={v}'" for k, v in overrides.items())
    return RECOVERY_DRIVER.format(ckpt=ckpt, steps=steps, extra=extra)


def _run_child(prog: str, env_extra: dict, timeout: float = 420.0):
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return subprocess.run(
        [sys.executable, "-c", prog], env=_child_env(env_extra),
        cwd=repo_root, capture_output=True, text=True, timeout=timeout,
    )


def _events(ckpt_dir: str, kind: str) -> list[dict]:
    return list(telemetry.read_events(
        os.path.join(ckpt_dir, "events.jsonl"), kind=kind, strict=False))


@pytest.mark.slow
@pytest.mark.slowest
def test_nan_recovers_in_process_no_relaunch(tmp_path):
    """Acceptance drill 1: DTF_FAULTS=nan_grads:30 poisons one batch; the
    run must detect at the next metric fetch, roll back to the last clean
    snapshot, skip the poisoned region, and FINISH — rc=0, one process,
    zero relaunches, with the full event trail on disk."""
    ckpt = str(tmp_path / "ckpt")
    prog = _driver(ckpt, steps=60, overrides={
        "resilience.snapshot_interval_steps": "10",
        "resilience.lr_rewarmup_steps": "5",
    })
    r = _run_child(prog, {"DTF_FAULTS": "nan_grads:30"})
    assert r.returncode == 0, r.stdout[-3000:] + r.stderr[-3000:]
    # in process: no checkpoint restore ever happened
    assert "Restored checkpoint at step" not in r.stdout + r.stderr

    anomalies = _events(ckpt, telemetry.KIND_ANOMALY)
    rollbacks = _events(ckpt, telemetry.KIND_ROLLBACK)
    skips = _events(ckpt, telemetry.KIND_BATCH_SKIPPED)
    assert len(anomalies) == 1 and anomalies[0]["step"] == 30
    assert anomalies[0]["health"]["anomaly"] == "non_finite_metric"
    assert len(rollbacks) == 1
    assert rollbacks[0]["health"] == {"from_step": 30, "to_step": 20,
                                      "consecutive_rollbacks": 1}
    assert skips[0]["health"]["batches"] == 10
    # a single run_id across every event: the same process start to finish
    run_ids = {e.get("run_id") for e in telemetry.read_events(
        os.path.join(ckpt, "events.jsonl"), strict=False)}
    assert len(run_ids) == 1
    # the ladder's rollup renders in the analyzer summary
    summary = telemetry.summarize_events(os.path.join(ckpt, "events.jsonl"))
    text = telemetry.format_run_summary(summary)
    assert "rollback: step 30 -> 20" in text
    assert "batches skipped: 10" in text


@pytest.mark.slow
@pytest.mark.slowest
def test_infeed_stall_watchdog_recovers(tmp_path):
    """Acceptance drill 2: a 6s pipeline stall mid-run (pull 25, well past
    compile and the prefetch buffer's coverage) surfaces as watchdog
    retries, and the loop rides through it to rc=0."""
    ckpt = str(tmp_path / "ckpt")
    prog = _driver(ckpt, steps=40, overrides={
        "resilience.infeed_deadline_s": "0.5",
        "resilience.infeed_retries": "20",
        "resilience.infeed_backoff_s": "0.1",
    })
    r = _run_child(prog, {"DTF_FAULTS": "stall_infeed:6s:25"})
    assert r.returncode == 0, r.stdout[-3000:] + r.stderr[-3000:]
    stalls = _events(ckpt, telemetry.KIND_INFEED_STALL)
    assert stalls, "watchdog never fired — the stall was absorbed silently"
    assert all(e["health"]["deadline_s"] == 0.5 for e in stalls)
    attempts = [e["health"]["attempt"] for e in stalls]
    assert attempts == sorted(attempts)  # one incident, monotone retries
    summary = telemetry.summarize_events(os.path.join(ckpt, "events.jsonl"))
    assert summary["recovery"]["infeed_stalls"] == len(stalls)


@pytest.mark.slow
@pytest.mark.slowest
def test_persistent_anomaly_escalates_distinct_rc(tmp_path):
    """Acceptance drill 3: repeat_nan re-poisons steps [30, 35) so every
    rollback lands back on a bad step; after max_rollbacks=2 the child
    must exit ANOMALY_ESCALATION_RC — not a generic crash rc — with the
    rollback trail in telemetry."""
    ckpt = str(tmp_path / "ckpt")
    prog = _driver(ckpt, steps=60, overrides={
        "resilience.snapshot_interval_steps": "10",
        "resilience.max_rollbacks": "2",
    })
    r = _run_child(prog, {"DTF_FAULTS": "repeat_nan:30:5"})
    assert r.returncode == supervision.ANOMALY_ESCALATION_RC, (
        f"rc={r.returncode}\n" + r.stdout[-3000:] + r.stderr[-3000:])
    assert "Persistent anomaly" in r.stdout + r.stderr
    rollbacks = _events(ckpt, telemetry.KIND_ROLLBACK)
    assert len(rollbacks) == 2  # the full budget, then escalation
    assert all(e["health"]["to_step"] == 20 for e in rollbacks)
    assert len(_events(ckpt, telemetry.KIND_ANOMALY)) == 3
