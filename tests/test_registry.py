"""User extension points: register_model / register_dataset.

The reference framework is a TEMPLATE — users plug in a model build
function and a dataset factory and the runtime does the rest (SURVEY.md
§1 L3/L4 extension points). These tests register both and drive the FULL
Trainer (mesh, sharded step, hooks, checkpoint restore, exact eval) over
the custom pair with zero framework changes.
"""

import numpy as np
import pytest

from distributed_tensorflow_framework_tpu.core.config import load_config
from distributed_tensorflow_framework_tpu.data import (
    get_dataset,
    register_dataset,
)
from distributed_tensorflow_framework_tpu.data.pipeline import (
    HostDataset,
    finite_array_eval,
    host_batch_size,
    image_np_dtype,
)
from distributed_tensorflow_framework_tpu.models import (
    get_model,
    register_model,
)

N_EVAL = 37


@pytest.fixture(scope="module", autouse=True)
def _register():
    import flax.linen as nn
    import jax.numpy as jnp

    @register_model("tiny_mlp")
    def build_model(config, *, bn_axis_name=None, mesh=None):
        class TinyMLP(nn.Module):
            num_classes: int

            @nn.compact
            def __call__(self, x, *, train: bool = True):
                x = x.reshape((x.shape[0], -1)).astype(jnp.float32)
                x = nn.relu(nn.Dense(32)(x))
                return nn.Dense(self.num_classes)(x)

        return TinyMLP(num_classes=config.num_classes)

    def _arrays(config, n, seed):
        rng = np.random.default_rng(seed)
        images = rng.standard_normal(
            (n, config.image_size, config.image_size, 1)).astype(np.float32)
        # Learnable rule so the loss can actually fall.
        labels = (images.mean(axis=(1, 2, 3)) > 0).astype(np.int32)
        return images, labels

    @register_dataset("toy_blobs")
    def build_dataset(config, process_index, process_count, *, train=True):
        b = host_batch_size(config.global_batch_size, process_count)
        if not train:
            images, labels = _arrays(config, N_EVAL, seed=99)
            return finite_array_eval(
                images, labels, batch=b, process_index=process_index,
                process_count=process_count,
                out_dtype=image_np_dtype(config.image_dtype))

        def make_iter(state):
            state.setdefault("batch", 0)
            while True:
                i = state["batch"]
                rng = np.random.default_rng((config.seed, process_index, i))
                images = rng.standard_normal(
                    (b, config.image_size, config.image_size, 1)
                ).astype(np.float32)
                labels = (images.mean(axis=(1, 2, 3)) > 0).astype(np.int32)
                state["batch"] = i + 1
                yield {"image": images, "label": labels}

        return HostDataset(
            make_iter,
            element_spec={
                "image": ((b, config.image_size, config.image_size, 1),
                          np.float32),
                "label": ((b,), np.int32),
            },
            initial_state={"batch": 0},
        )

    yield
    # Registries are process-global with no unregister API — restore
    # isolation for any later test in the same pytest process.
    from distributed_tensorflow_framework_tpu import data as data_pkg
    from distributed_tensorflow_framework_tpu import models as models_pkg

    models_pkg._CUSTOM_MODELS.pop("tiny_mlp", None)
    data_pkg._CUSTOM_DATASETS.pop("toy_blobs", None)


def test_duplicate_and_shadow_registrations_rejected():
    with pytest.raises(ValueError, match="already registered"):
        register_model("tiny_mlp")(lambda config, **kw: None)
    with pytest.raises(ValueError, match="shadows a built-in"):
        register_model("resnet50")(lambda config, **kw: None)
    with pytest.raises(ValueError, match="already registered"):
        register_dataset("toy_blobs")(lambda *a, **kw: None)
    with pytest.raises(ValueError, match="shadows a built-in"):
        register_dataset("imagenet")(lambda *a, **kw: None)


def test_registered_pair_resolves():
    from distributed_tensorflow_framework_tpu.core.config import (
        DataConfig,
        ModelConfig,
    )

    model = get_model(ModelConfig(name="tiny_mlp", num_classes=2))
    assert model.num_classes == 2
    ds = get_dataset(DataConfig(name="toy_blobs", global_batch_size=8,
                                image_size=8, channels=1))
    batch = next(ds)
    assert batch["image"].shape == (8, 8, 8, 1)


def test_custom_pair_through_full_trainer(devices, tmp_path):
    """Custom model + custom dataset drive the whole runtime: sharded
    training on the 8-device mesh, loss falls on the learnable rule,
    checkpoint auto-restore resumes, final exact eval covers the full
    custom validation set."""
    from distributed_tensorflow_framework_tpu.train import Trainer

    base = {
        "name": "custom-pair",
        "mesh": {"data": 8},
        "model": {"name": "tiny_mlp", "num_classes": 2, "dtype": "float32"},
        "data": {"name": "toy_blobs", "global_batch_size": 64,
                 "image_size": 8, "channels": 1, "seed": 5},
        "optimizer": {"name": "sgd_momentum", "learning_rate": 0.1},
        "train": {"total_steps": 60, "log_interval": 20, "eval_steps": 2},
        "checkpoint": {"directory": str(tmp_path / "ck"),
                       "save_interval_steps": 30},
    }
    t = Trainer(load_config(base=dict(base)))
    metrics = t.train()
    assert metrics["loss"] < 0.4, metrics  # learnable rule actually learned
    results = t.evaluate()
    assert results["eval_examples"] == N_EVAL  # full custom set, once
    assert results["eval_top1"] > 0.8

    # Relaunch: auto-restores the final checkpoint, skips training,
    # reproduces the eval bit-for-bit.
    t2 = Trainer(load_config(base=dict(base)))
    t2.build()
    assert t2.host_step == 60
    results2 = t2.evaluate()
    assert results2 == results


def test_builtin_name_patterns_reserved():
    # The whole resnet-N pattern is reserved, not just shipped depths.
    with pytest.raises(ValueError, match="shadows a built-in"):
        register_model("resnet7")(lambda config, **kw: None)
    with pytest.raises(ValueError, match="shadows a built-in"):
        register_dataset("synthetic_foo")(lambda *a, **kw: None)
