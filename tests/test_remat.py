"""Activation rematerialization (model.remat → nn.remat encoder layers).

jax.checkpoint replays the same ops in the backward pass, so remat must be
numerically EXACT: identical logits and identical gradients, just less
live-activation memory.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_framework_tpu.core.config import ModelConfig
from distributed_tensorflow_framework_tpu.models import get_model


def _tiny_bert(remat: bool) -> ModelConfig:
    return ModelConfig(
        name="bert", vocab_size=256, hidden_size=32, num_layers=3,
        num_heads=4, mlp_dim=64, max_seq_len=32, dtype="float32",
        dropout_rate=0.1, remat=remat,
    )


@pytest.mark.slow
def test_remat_exact_logits_and_grads(devices):
    ids = jnp.asarray(np.random.default_rng(0).integers(1, 256, (2, 16)),
                      jnp.int32)
    mask = jnp.ones((2, 16), jnp.int32)
    rng = jax.random.key(0)

    models = [get_model(_tiny_bert(r)) for r in (False, True)]
    vs = models[0].init({"params": rng, "dropout": rng}, ids, mask,
                        train=False)
    # Same params drive both variants (remat adds no parameters).
    outs, grads = [], []
    for m in models:
        def loss_fn(params):
            logits = m.apply({"params": params}, ids, mask, train=True,
                             rngs={"dropout": jax.random.key(7)})
            return (logits.astype(jnp.float32) ** 2).mean()

        out = m.apply(vs, ids, mask, train=False)
        l, g = jax.value_and_grad(loss_fn)(vs["params"])
        outs.append(np.asarray(out))
        grads.append(jax.device_get(g))

    np.testing.assert_array_equal(outs[0], outs[1])
    for a, b in zip(jax.tree.leaves(grads[0]), jax.tree.leaves(grads[1])):
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7)


def test_remat_rejected_for_conv_models():
    with pytest.raises(ValueError, match="transformer"):
        get_model(ModelConfig(name="resnet50", remat=True))


def test_remat_rejected_with_pipeline():
    cfg = _tiny_bert(True)
    cfg.pipeline_stages = 2
    with pytest.raises(ValueError, match="pipelined"):
        get_model(cfg)
