"""Activation rematerialization (model.remat → nn.remat encoder layers).

jax.checkpoint replays the same ops in the backward pass, so remat must be
numerically EXACT: identical logits and identical gradients, just less
live-activation memory.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_framework_tpu.core.config import ModelConfig
from distributed_tensorflow_framework_tpu.models import get_model


def _tiny_bert(remat: bool) -> ModelConfig:
    return ModelConfig(
        name="bert", vocab_size=256, hidden_size=32, num_layers=3,
        num_heads=4, mlp_dim=64, max_seq_len=32, dtype="float32",
        dropout_rate=0.1, remat=remat,
    )


@pytest.mark.slow
def test_remat_exact_logits_and_grads(devices):
    ids = jnp.asarray(np.random.default_rng(0).integers(1, 256, (2, 16)),
                      jnp.int32)
    mask = jnp.ones((2, 16), jnp.int32)
    rng = jax.random.key(0)

    models = [get_model(_tiny_bert(r)) for r in (False, True)]
    vs = models[0].init({"params": rng, "dropout": rng}, ids, mask,
                        train=False)
    # Same params drive both variants (remat adds no parameters).
    outs, grads = [], []
    for m in models:
        def loss_fn(params):
            logits = m.apply({"params": params}, ids, mask, train=True,
                             rngs={"dropout": jax.random.key(7)})
            return (logits.astype(jnp.float32) ** 2).mean()

        out = m.apply(vs, ids, mask, train=False)
        l, g = jax.value_and_grad(loss_fn)(vs["params"])
        outs.append(np.asarray(out))
        grads.append(jax.device_get(g))

    np.testing.assert_array_equal(outs[0], outs[1])
    for a, b in zip(jax.tree.leaves(grads[0]), jax.tree.leaves(grads[1])):
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7)


def test_remat_rejected_for_unwired_models():
    with pytest.raises(ValueError, match="transformer"):
        get_model(ModelConfig(name="lenet5", remat=True))
    with pytest.raises(ValueError, match="transformer"):
        get_model(ModelConfig(name="inception_v3", remat=True))


@pytest.mark.slow
def test_resnet_remat_exact_logits_grads_and_bn_stats(devices):
    """Per-block remat on the ResNet stack (the byte lever for the
    HBM-bound ImageNet step): identical logits, gradients AND BatchNorm
    running-stat updates — jax.checkpoint replays, never diverges."""
    x = jnp.asarray(
        np.random.default_rng(1).standard_normal((2, 32, 32, 3)), jnp.float32)

    models = [
        get_model(ModelConfig(name="resnet18_cifar", num_classes=10,
                              dtype="float32", remat=r))
        for r in (False, True)
    ]
    vs = models[0].init(jax.random.key(0), x, train=False)
    outs, grads, stats = [], [], []
    for m in models:
        def loss_fn(params):
            logits, new_state = m.apply(
                {"params": params, "batch_stats": vs["batch_stats"]},
                x, train=True, mutable=["batch_stats"])
            return (logits.astype(jnp.float32) ** 2).mean(), new_state

        out = m.apply(vs, x, train=False)
        (l, new_state), g = jax.value_and_grad(loss_fn, has_aux=True)(
            vs["params"])
        outs.append(np.asarray(out))
        grads.append(jax.device_get(g))
        stats.append(jax.device_get(new_state["batch_stats"]))

    np.testing.assert_array_equal(outs[0], outs[1])
    for a, b in zip(jax.tree.leaves(grads[0]), jax.tree.leaves(grads[1])):
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7)
    for a, b in zip(jax.tree.leaves(stats[0]), jax.tree.leaves(stats[1])):
        np.testing.assert_array_equal(a, b)


def test_remat_rejected_with_pipeline():
    cfg = _tiny_bert(True)
    cfg.pipeline_stages = 2
    with pytest.raises(ValueError, match="pipelined"):
        get_model(cfg)
