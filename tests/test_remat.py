"""Activation rematerialization (model.remat → nn.remat on per-model blocks).

jax.checkpoint replays the same OPS in the backward pass. On the small
BERT/ResNet stacks the replay happens to be bitwise (pinned below); XLA
is free to fuse the wrapped computation differently though, and on the
deep Inception BN cascade the measured ~1e-6/block refusion noise
amplifies chaotically in train mode — so Inception pins block-level
parity + eval equality + finite training instead of whole-model bitwise
gradients (see test_inception_remat_block_parity_and_trains).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_framework_tpu.core.config import ModelConfig
from distributed_tensorflow_framework_tpu.models import get_model


def _tiny_bert(remat: bool) -> ModelConfig:
    return ModelConfig(
        name="bert", vocab_size=256, hidden_size=32, num_layers=3,
        num_heads=4, mlp_dim=64, max_seq_len=32, dtype="float32",
        dropout_rate=0.1, remat=remat,
    )


@pytest.mark.slow
def test_remat_exact_logits_and_grads(devices):
    ids = jnp.asarray(np.random.default_rng(0).integers(1, 256, (2, 16)),
                      jnp.int32)
    mask = jnp.ones((2, 16), jnp.int32)
    rng = jax.random.key(0)

    models = [get_model(_tiny_bert(r)) for r in (False, True)]
    vs = models[0].init({"params": rng, "dropout": rng}, ids, mask,
                        train=False)
    # Same params drive both variants (remat adds no parameters).
    outs, grads = [], []
    for m in models:
        def loss_fn(params):
            logits = m.apply({"params": params}, ids, mask, train=True,
                             rngs={"dropout": jax.random.key(7)})
            return (logits.astype(jnp.float32) ** 2).mean()

        out = m.apply(vs, ids, mask, train=False)
        l, g = jax.value_and_grad(loss_fn)(vs["params"])
        outs.append(np.asarray(out))
        grads.append(jax.device_get(g))

    np.testing.assert_array_equal(outs[0], outs[1])
    for a, b in zip(jax.tree.leaves(grads[0]), jax.tree.leaves(grads[1])):
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7)


def test_remat_rejected_for_unwired_models():
    with pytest.raises(ValueError, match="transformer"):
        get_model(ModelConfig(name="lenet5", remat=True))


def test_remat_policy_rejected_off_resnet():
    # conv_saved keys on the ConvBN tag inside the resnet blocks; other
    # models (and remat=false) must reject it, not silently ignore it.
    with pytest.raises(ValueError, match="remat_policy"):
        get_model(ModelConfig(name="bert", remat=True,
                              remat_policy="conv_saved"))
    with pytest.raises(ValueError, match="remat_policy"):
        get_model(ModelConfig(name="resnet50", remat=False,
                              remat_policy="conv_saved"))
    with pytest.raises(ValueError, match="conv_saved"):
        get_model(ModelConfig(name="resnet50", remat=True,
                              remat_policy="typo"))


@pytest.mark.slow
def test_inception_remat_block_parity_and_trains(devices):
    """Per-block remat on the Inception mixed/reduction blocks.

    The remat transform is not guaranteed BITWISE on this backend (XLA
    may fuse the wrapped forward differently — measured ~1e-6 per
    block), and Inception's deep train-mode BatchNorm cascade chaotically
    amplifies a 1e-6 input perturbation to O(10%) logits at random init —
    so a whole-model gradient comparison cannot distinguish refusion
    noise from a real bug. Pin instead what IS meaningful: (a) one
    wrapped block's forward+gradients match the plain block tightly,
    (b) the full remat model's EVAL forward (running-stat BN, the
    non-chaotic mode) is bit-equal, (c) the remat model trains to a
    finite loss through the full train step."""
    import flax.linen as nn

    from distributed_tensorflow_framework_tpu.models.inception import InceptionA

    # (a) single-block parity, fwd + grads.
    xb = jnp.asarray(
        np.random.default_rng(0).standard_normal((2, 17, 17, 64)), jnp.float32)
    plain = InceptionA(32, train=True, dtype=jnp.float32)
    remat = nn.remat(InceptionA)(32, train=True, dtype=jnp.float32)
    vsb = plain.init(jax.random.key(0), xb)

    def block_loss(m):
        def f(params):
            y, _ = m.apply({"params": params,
                            "batch_stats": vsb["batch_stats"]},
                           xb, mutable=["batch_stats"])
            return (y.astype(jnp.float32) ** 2).mean()
        return f

    for (a, b) in zip(
            jax.tree.leaves(jax.grad(block_loss(plain))(vsb["params"])),
            jax.tree.leaves(jax.grad(block_loss(remat))(vsb["params"]))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)

    # (b) full-model eval forward bit-equal; (c) trains finite.
    x = jnp.asarray(
        np.random.default_rng(2).standard_normal((2, 83, 83, 3)), jnp.float32)
    models = [
        get_model(ModelConfig(name="inception_v3", num_classes=10,
                              dtype="float32", remat=r))
        for r in (False, True)
    ]
    vs = models[0].init(jax.random.key(0), x, train=False)
    # Eval (running-stat BN) avoids the chaotic amplification; allow the
    # per-block refusion noise itself rather than demanding bitwise.
    np.testing.assert_allclose(
        np.asarray(models[0].apply(vs, x, train=False)),
        np.asarray(models[1].apply(vs, x, train=False)),
        rtol=1e-5, atol=1e-5)

    def loss_fn(params):
        out, _ = models[1].apply(
            {"params": params, "batch_stats": vs["batch_stats"]},
            x, train=True, mutable=["batch_stats"],
            rngs={"dropout": jax.random.key(3)})
        return ((out["logits"].astype(jnp.float32) ** 2).mean()
                + 0.4 * (out["aux_logits"] ** 2).mean())

    loss, grads = jax.value_and_grad(loss_fn)(vs["params"])
    assert np.isfinite(float(loss))
    assert all(np.isfinite(np.asarray(g)).all()
               for g in jax.tree.leaves(grads))


@pytest.mark.slow
def test_resnet_remat_exact_logits_grads_and_bn_stats(devices):
    """Per-block remat on the ResNet stack (the byte lever for the
    HBM-bound ImageNet step): identical logits, gradients AND BatchNorm
    running-stat updates — jax.checkpoint replays, never diverges.
    Covers both replay policies — "full" (save nothing) and "conv_saved"
    (keep conv outputs, replay only the BN/ReLU tail) — against ONE
    shared non-remat baseline."""
    x = jnp.asarray(
        np.random.default_rng(1).standard_normal((2, 32, 32, 3)), jnp.float32)

    def run(remat, policy):
        m = get_model(ModelConfig(name="resnet18_cifar", num_classes=10,
                                  dtype="float32", remat=remat,
                                  remat_policy=policy))
        def loss_fn(params):
            logits, new_state = m.apply(
                {"params": params, "batch_stats": vs["batch_stats"]},
                x, train=True, mutable=["batch_stats"])
            return (logits.astype(jnp.float32) ** 2).mean(), new_state

        out = m.apply(vs, x, train=False)
        (_, new_state), g = jax.value_and_grad(loss_fn, has_aux=True)(
            vs["params"])
        return (np.asarray(out), jax.device_get(g),
                jax.device_get(new_state["batch_stats"]))

    vs = get_model(ModelConfig(name="resnet18_cifar", num_classes=10,
                               dtype="float32")).init(
        jax.random.key(0), x, train=False)
    base_out, base_grads, base_stats = run(False, "full")
    for policy in ("full", "conv_saved"):
        out, grads, stats = run(True, policy)
        np.testing.assert_array_equal(base_out, out, err_msg=policy)
        for a, b in zip(jax.tree.leaves(base_grads), jax.tree.leaves(grads)):
            np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7,
                                       err_msg=policy)
        for a, b in zip(jax.tree.leaves(base_stats), jax.tree.leaves(stats)):
            np.testing.assert_array_equal(a, b, err_msg=policy)


def test_remat_rejected_with_pipeline():
    cfg = _tiny_bert(True)
    cfg.pipeline_stages = 2
    with pytest.raises(ValueError, match="pipelined"):
        get_model(cfg)
