"""Elastic resharding — checkpoints and supervision across mesh changes.

ISSUE 6 tentpole: a checkpoint written under one mesh restores onto a
different one (ckpt/reshard.py), and the supervisor's rc-84 contract
(core/supervision.py) refits the largest valid mesh onto a changed device
set. Fast tests pin the pure arithmetic (fit_axis_sizes,
rescale_for_devices, device reports, fault parsing) and one cheap LeNet
cross-mesh restore; the slow class runs the full parity matrix on
sharded BERT states.
"""

import json
import os

import jax
import numpy as np
import pytest

from distributed_tensorflow_framework_tpu.ckpt import (
    CheckpointManager,
    MeshTopologyError,
)
from distributed_tensorflow_framework_tpu.ckpt import manifest as mf
from distributed_tensorflow_framework_tpu.ckpt import reshard
from distributed_tensorflow_framework_tpu.core import (
    faults,
    supervision,
    telemetry,
)
from distributed_tensorflow_framework_tpu.core.config import load_config
from distributed_tensorflow_framework_tpu.core.mesh import (
    MESH_AXES,
    MeshSizeError,
    create_mesh,
    fit_mesh,
)
from distributed_tensorflow_framework_tpu.data import get_dataset
from distributed_tensorflow_framework_tpu.data.infeed import to_global
from distributed_tensorflow_framework_tpu.train.step import StepBuilder


# -- pure arithmetic (stdlib supervision layer) ---------------------------
def test_axis_order_mirrors_mesh_axes():
    # supervision.py must stay stdlib-importable, so it carries its own
    # copy of the axis order; this pin is what stops the two drifting.
    assert supervision.MESH_AXIS_ORDER == MESH_AXES


def test_fit_axis_sizes_shrink_data():
    assert supervision.fit_axis_sizes({"data": 8}, 4) == {"data": 4}


def test_fit_axis_sizes_grow_data():
    assert supervision.fit_axis_sizes({"data": 4}, 8) == {"data": 8}


def test_fit_axis_sizes_preserves_inner_axes_first():
    # 4 devices cannot hold {fsdp:2, pipe:4}; among the feasible divisor
    # combinations the innermost (model-ward) axis keeps its size:
    # pipe:4 survives, fsdp drops to 1.
    fit = supervision.fit_axis_sizes({"data": 1, "fsdp": 2, "pipe": 4}, 4)
    assert fit == {"data": 1, "fsdp": 1, "pipe": 4}


def test_fit_axis_sizes_keeps_structure_when_data_absorbs():
    fit = supervision.fit_axis_sizes({"data": 2, "fsdp": 4}, 8)
    assert fit == {"data": 2, "fsdp": 4}
    fit = supervision.fit_axis_sizes({"data": 2, "fsdp": 4}, 4)
    assert fit == {"data": 1, "fsdp": 4}


def test_fit_axis_sizes_uses_all_devices():
    for n in (1, 2, 3, 4, 6, 8, 12):
        fit = supervision.fit_axis_sizes(
            {"data": 8, "fsdp": 2, "pipe": 2}, n)
        prod = 1
        for v in fit.values():
            prod *= v
        assert prod == n, fit


def test_fit_axis_sizes_treats_minus_one_as_absorbing():
    fit = supervision.fit_axis_sizes({"data": -1, "model": 2}, 6)
    assert fit == {"data": 3, "model": 2}


def test_fit_axis_sizes_errors():
    with pytest.raises(ValueError):
        supervision.fit_axis_sizes({"data": 8}, 0)
    with pytest.raises(ValueError):
        supervision.fit_axis_sizes({"data": 8, "pipe": 0}, 4)
    with pytest.raises(ValueError, match="no mesh"):
        # No data axis to absorb: pipe's divisors {1, 2, 4} never
        # multiply to 3.
        supervision.fit_axis_sizes({"pipe": 4}, 3)


def test_fit_mesh_delegates(devices):
    from distributed_tensorflow_framework_tpu.core.config import MeshConfig

    fit = fit_mesh(MeshConfig(data=8), 4)
    assert fit["data"] == 4
    assert fit == supervision.fit_axis_sizes(
        MeshConfig(data=8).axis_sizes(), 4)


def test_rescale_preserves_effective_batch_on_shrink():
    # The acceptance drill's numbers: 64/1 at dp=8 -> 32/2 at dp=4
    # (per-device batch constant, effective batch 64 preserved).
    assert supervision.rescale_for_devices(64, 1, 8, 4) == (32, 2, True)


def test_rescale_growth_and_fallbacks():
    # Growth with accum slack: per-device preserved.
    assert supervision.rescale_for_devices(32, 4, 4, 8) == (64, 2, True)
    # Growth without accum slack: keep the global batch (still preserved).
    assert supervision.rescale_for_devices(64, 1, 8, 16) == (64, 1, True)
    # Nothing divides: unchanged, flagged not-preserved.
    assert supervision.rescale_for_devices(63, 1, 8, 4) == (63, 1, False)
    # No-op resize.
    assert supervision.rescale_for_devices(64, 2, 4, 4) == (64, 2, True)


def test_mask_host_device_count():
    masked = supervision.mask_host_device_count("", 4)
    assert masked == "--xla_force_host_platform_device_count=4"
    masked = supervision.mask_host_device_count(
        "--xla_force_host_platform_device_count=8 --foo=1", 2)
    assert masked == "--xla_force_host_platform_device_count=2 --foo=1"


def test_device_report_roundtrip(tmp_path):
    path = supervision.write_device_report(
        str(tmp_path / "ck"), visible_devices=4, needed=8,
        mesh={"data": 8})
    assert os.path.basename(path) == supervision.DEVICE_REPORT_NAME
    report = supervision.read_device_report(str(tmp_path / "ck"))
    assert report["visible_devices"] == 4
    assert report["needed"] == 8
    assert report["mesh"] == {"data": 8}
    assert supervision.read_device_report(str(tmp_path / "absent")) is None
    with open(path, "w") as fh:
        fh.write("{torn")
    assert supervision.read_device_report(str(tmp_path / "ck")) is None


def test_drop_devices_fault_parse():
    plan = faults.FaultPlan.parse("drop_devices:4:2")
    (fault,) = plan.faults
    assert fault.point == "relaunch"
    assert fault.devices == 4
    assert fault.step == 2
    # Default relaunch ordinal is 1 (the first launch).
    assert faults.FaultPlan.parse("drop_devices:4").faults[0].step == 1
    with pytest.raises(ValueError):
        faults.FaultPlan.parse("drop_devices:zero")
    with pytest.raises(ValueError):
        faults.FaultPlan.parse("drop_devices:0:1")


def test_drop_devices_fires_only_at_its_attempt():
    plan = faults.FaultPlan.parse("drop_devices:4:2")
    assert plan.fire("relaunch", step=1) == []
    handled = plan.fire("relaunch", step=2)
    assert [f.kind for f in handled] == ["drop_devices"]
    assert plan.fire("relaunch", step=2) == []  # once only


def test_parse_training_params_inside_dash_c_program():
    from scripts.train_resilient import parse_training_params

    cmd = ["python", "-c",
           "from x import main; main(['--set','mesh.data=8',"
           "'--set','mesh.pipe=2','--set','data.global_batch_size=48',"
           "'--set','train.grad_accum_steps=3'])"]
    sizes, batch, accum = parse_training_params(cmd)
    assert sizes["data"] == 8 and sizes["pipe"] == 2
    assert (batch, accum) == (48, 3)


# -- topology records and the restore gate --------------------------------
def test_describe_and_normalize_axes():
    assert reshard.describe_axes({"data": 8, "fsdp": 1}) == "{data:8}"
    assert reshard.describe_axes({"data": 1}) == "{1 device}"
    assert reshard.axes_equal({"data": 4}, {"data": 4, "pipe": 1})
    assert not reshard.axes_equal({"data": 4}, {"data": 8})
    assert not reshard.axes_equal(None, {"data": 4})


def test_mesh_size_error_names_counts(devices):
    from distributed_tensorflow_framework_tpu.core.config import MeshConfig

    with pytest.raises(MeshSizeError) as ei:
        create_mesh(MeshConfig(data=8), devices=devices[:4])
    assert ei.value.available == 4
    assert ei.value.needed == 8
    assert "8 devices but 4 are available" in str(ei.value)


def test_mesh_topology_error_names_both_meshes_and_knob():
    err = MeshTopologyError(
        {"data": 8}, {"data": 4}, directory="/ck", step=30)
    msg = str(err)
    assert "{data:8}" in msg and "{data:4}" in msg
    assert "checkpoint.allow_reshard" in msg
    assert err.saved_axes == {"data": 8}
    assert err.requested_axes == {"data": 4}


def _lenet_state(devices, n, *, seed=0, batch_size=64):
    cfg = load_config(base={
        "name": "reshard-lenet",
        "mesh": {"data": n},
        "model": {"name": "lenet5", "num_classes": 10, "dtype": "float32"},
        "data": {"name": "synthetic_images", "global_batch_size": batch_size,
                 "image_size": 28, "channels": 1},
        "optimizer": {"name": "sgd_momentum", "learning_rate": 0.05},
        "train": {"total_steps": 4},
    })
    mesh = create_mesh(cfg.mesh, devices=devices[:n])
    builder = StepBuilder(cfg, mesh)
    batch = to_global(next(get_dataset(cfg.data)), mesh)
    state = builder.init_state(seed, batch)
    return cfg, mesh, state


def _save(cfg, mesh, state, ckpt_dir, *, step=1):
    cfg.checkpoint.directory = ckpt_dir
    cfg.checkpoint.async_save = False
    mgr = CheckpointManager(cfg.checkpoint, mesh=mesh)
    assert mgr.save(step, state)
    mgr.wait_until_finished()
    mgr.close()


def _assert_trees_equal(saved, restored):
    s_leaves = jax.tree.leaves(jax.device_get(saved))
    r_leaves = jax.tree.leaves(jax.device_get(restored))
    assert len(s_leaves) == len(r_leaves)
    for a, b in zip(s_leaves, r_leaves):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_manifest_records_mesh_topology(devices, tmp_path):
    cfg, mesh, state = _lenet_state(devices, 8)
    _save(cfg, mesh, state, str(tmp_path / "ck"))
    manifest = mf.read_manifest(str(tmp_path / "ck" / "1"))
    record = manifest[reshard.MESH_RECORD_KEY]
    assert record["axes"]["data"] == 8
    assert record["device_count"] == 8
    assert record["process_count"] == 1
    assert record["spec_digest"] == reshard.spec_digest(state)


def test_restore_refuses_mesh_change_without_knob(devices, tmp_path):
    cfg, mesh, state = _lenet_state(devices, 8)
    _save(cfg, mesh, state, str(tmp_path / "ck"))
    cfg_b, _, template = _lenet_state(devices, 4, seed=9)
    cfg_b.checkpoint.directory = str(tmp_path / "ck")
    cfg_b.checkpoint.async_save = False
    mgr = CheckpointManager(cfg_b.checkpoint)
    with pytest.raises(MeshTopologyError) as ei:
        mgr.restore(template)
    mgr.close()
    assert "{data:8}" in str(ei.value) and "{data:4}" in str(ei.value)


def test_reshard_restore_lenet_8_to_4(devices, tmp_path):
    # The cheap end-to-end slice of the parity matrix; the sharded BERT
    # pairs live in the slow class below.
    cfg, mesh, state = _lenet_state(devices, 8)
    _save(cfg, mesh, state, str(tmp_path / "ck"))
    cfg_b, mesh_b, template = _lenet_state(devices, 4, seed=9)
    cfg_b.checkpoint.directory = str(tmp_path / "ck")
    cfg_b.checkpoint.async_save = False
    cfg_b.checkpoint.allow_reshard = True
    events = str(tmp_path / "events.jsonl")
    writer = telemetry.TelemetryWriter(events)
    mgr = CheckpointManager(
        cfg_b.checkpoint, telemetry_writer=writer, mesh=mesh_b)
    restored = mgr.restore(template)
    mgr.close()
    writer.close()
    assert restored is not None
    _assert_trees_equal(state.params, restored.params)
    _assert_trees_equal(state.opt_state, restored.opt_state)
    # Restored leaves live on the NEW mesh.
    leaf = jax.tree.leaves(restored.params)[0]
    assert dict(leaf.sharding.mesh.shape)["data"] == 4
    # The reshard is telemetered for analyze_trace.py.
    kinds = [ev["kind"] for ev in telemetry.read_events(events)]
    assert telemetry.KIND_CKPT_RESHARDED in kinds


def test_legacy_manifest_restores_with_warning(devices, tmp_path, caplog):
    cfg, mesh, state = _lenet_state(devices, 8)
    _save(cfg, mesh, state, str(tmp_path / "ck"))
    # Strip the topology record: a pre-elastic checkpoint. The manifest
    # file itself is not payload-hashed, so the rewrite stays committed.
    step_dir = str(tmp_path / "ck" / "1")
    manifest = mf.read_manifest(step_dir)
    manifest.pop(reshard.MESH_RECORD_KEY)
    with open(os.path.join(step_dir, mf.MANIFEST_NAME), "w") as fh:
        json.dump(manifest, fh)
    cfg_b, _, template = _lenet_state(devices, 4, seed=9)
    cfg_b.checkpoint.directory = str(tmp_path / "ck")
    cfg_b.checkpoint.async_save = False
    # Knob OFF: a legacy manifest must not brick the restore — one-line
    # warning, no gate (there is nothing recorded to gate on).
    mgr = CheckpointManager(cfg_b.checkpoint)
    with caplog.at_level("WARNING"):
        restored = mgr.restore(template)
    mgr.close()
    assert restored is not None
    assert any("no mesh topology record" in r.message for r in caplog.records)
    _assert_trees_equal(state.params, restored.params)


def test_same_mesh_restore_has_no_gate(devices, tmp_path):
    cfg, mesh, state = _lenet_state(devices, 8)
    _save(cfg, mesh, state, str(tmp_path / "ck"))
    cfg_b, _, template = _lenet_state(devices, 8, seed=9)
    cfg_b.checkpoint.directory = str(tmp_path / "ck")
    cfg_b.checkpoint.async_save = False
    mgr = CheckpointManager(cfg_b.checkpoint)  # allow_reshard defaults off
    restored = mgr.restore(template)
    mgr.close()
    _assert_trees_equal(state.params, restored.params)


def test_validate_restored_catches_shape_drift():
    template = {"w": np.zeros((4, 4), np.float32)}
    ok = reshard.validate_restored(
        template, {"w": np.zeros((4, 4), np.float32)}, step=1)
    assert ok == 1
    with pytest.raises(ValueError, match="global leaf shapes"):
        reshard.validate_restored(
            template, {"w": np.zeros((2, 4), np.float32)}, step=1)
    with pytest.raises(ValueError, match="tree structure"):
        reshard.validate_restored(
            template, {"w2": np.zeros((4, 4), np.float32)}, step=1)


# -- quantized-collective residual across save/restore/reshard ------------
def _lenet_state_int8(devices, n, *, seed=0, steps=2):
    # ISSUE 7: int8 collectives keep a per-replica error-feedback residual
    # (TrainState.collective_residual) that must survive checkpointing.
    # A couple of real steps make the residual nonzero so the assertions
    # below cannot pass vacuously.
    cfg = load_config(base={
        "name": "reshard-lenet-int8",
        "mesh": {"data": n},
        "model": {"name": "lenet5", "num_classes": 10, "dtype": "float32"},
        "data": {"name": "synthetic_images", "global_batch_size": 64,
                 "image_size": 28, "channels": 1},
        "optimizer": {"name": "sgd_momentum", "learning_rate": 0.05},
        "train": {"total_steps": 4, "spmd_mode": "shard_map"},
        "parallel": {"collective_dtype": "int8",
                     "collective_block_size": 64},
    })
    mesh = create_mesh(cfg.mesh, devices=devices[:n])
    builder = StepBuilder(cfg, mesh)
    batch = to_global(next(get_dataset(cfg.data)), mesh)
    state = builder.init_state(seed, batch)
    if steps:
        step_fn = builder.make_train_step(batch)
        for _ in range(steps):
            state, _ = step_fn(state, batch)
    return cfg, mesh, builder, batch, state


def test_residual_roundtrip_same_mesh_bit_exact(devices, tmp_path):
    cfg, mesh, builder, batch, state = _lenet_state_int8(devices, 8)
    res = jax.tree.leaves(jax.device_get(state.collective_residual))
    assert res and any(np.abs(np.asarray(r)).max() > 0 for r in res)
    _save(cfg, mesh, state, str(tmp_path / "ck"))
    mgr = CheckpointManager(cfg.checkpoint)
    restored = mgr.restore(builder.init_state(0, batch))
    mgr.close()
    assert restored is not None
    _assert_trees_equal(state.collective_residual,
                        restored.collective_residual)
    _assert_trees_equal(state.params, restored.params)


def test_reshard_8_to_4_folds_residual_sum_preserving(devices, tmp_path):
    # A topology change cannot keep per-replica residuals as-is (the
    # replica axis shrank); reshard.fold_residual folds rows so the SUM
    # of pending corrections — the only quantity the EF update consumes —
    # is preserved exactly.
    cfg, mesh, _, _, state = _lenet_state_int8(devices, 8)
    _save(cfg, mesh, state, str(tmp_path / "ck"))
    old_sums = [np.asarray(r).sum(axis=0) for r in
                jax.tree.leaves(jax.device_get(state.collective_residual))]
    assert any(np.abs(s).max() > 0 for s in old_sums)
    cfg_b, mesh_b, builder_b, batch_b, _ = _lenet_state_int8(
        devices, 4, seed=9, steps=0)
    cfg_b.checkpoint.directory = str(tmp_path / "ck")
    cfg_b.checkpoint.async_save = False
    cfg_b.checkpoint.allow_reshard = True
    mgr = CheckpointManager(cfg_b.checkpoint, mesh=mesh_b)
    restored = mgr.restore(builder_b.init_state(0, batch_b))
    mgr.close()
    assert restored is not None
    new_res = jax.tree.leaves(jax.device_get(restored.collective_residual))
    assert new_res and all(r.shape[0] == 4 for r in new_res)
    for old_sum, new in zip(old_sums, new_res):
        np.testing.assert_allclose(
            new.sum(axis=0), old_sum, rtol=1e-6, atol=1e-7)
    _assert_trees_equal(state.params, restored.params)


# -- ZeRO stacked opt state across a grid change --------------------------
def _lenet_state_zero(devices, n, *, seed=0, steps=1):
    # ISSUE 9: zero_sharding='shard_map' stacks every optimizer slot as
    # (n, ceil(S/n)) rows over the data×fsdp replicas. A checkpoint
    # written at one grid must refold host-side to the new replica count
    # on a resharded restore (ckpt/reshard.refold_zero_opt_state).
    cfg = load_config(base={
        "name": "reshard-lenet-zero",
        "mesh": {"data": n},
        "model": {"name": "lenet5", "num_classes": 10, "dtype": "float32"},
        "data": {"name": "synthetic_images", "global_batch_size": 64,
                 "image_size": 28, "channels": 1},
        "optimizer": {"name": "adam", "learning_rate": 0.01,
                      "zero_sharding": "shard_map"},
        "train": {"total_steps": 4, "spmd_mode": "shard_map"},
    })
    mesh = create_mesh(cfg.mesh, devices=devices[:n])
    builder = StepBuilder(cfg, mesh)
    batch = to_global(next(get_dataset(cfg.data)), mesh)
    state = builder.init_state(seed, batch)
    if steps:
        step_fn = builder.make_train_step(batch)
        for _ in range(steps):
            state, _ = step_fn(state, batch)
    return cfg, mesh, builder, batch, state


def test_zero_opt_state_reshard_8_to_4(devices, tmp_path):
    from distributed_tensorflow_framework_tpu.parallel import zero

    cfg, mesh, _, _, state = _lenet_state_zero(devices, 8)
    _save(cfg, mesh, state, str(tmp_path / "ck"))
    cfg_b, mesh_b, builder_b, batch_b, _ = _lenet_state_zero(
        devices, 4, seed=9, steps=0)
    cfg_b.checkpoint.directory = str(tmp_path / "ck")
    cfg_b.checkpoint.async_save = False
    cfg_b.checkpoint.allow_reshard = True
    events = str(tmp_path / "events.jsonl")
    writer = telemetry.TelemetryWriter(events)
    mgr = CheckpointManager(
        cfg_b.checkpoint, telemetry_writer=writer, mesh=mesh_b)
    restored = mgr.restore(builder_b.init_state(0, batch_b))
    mgr.close()
    writer.close()
    assert restored is not None
    _assert_trees_equal(state.params, restored.params)
    kinds = [ev["kind"] for ev in telemetry.read_events(events)]
    assert telemetry.KIND_CKPT_RESHARDED in kinds

    # Slots refolded to the NEW grid: (4, ceil(S/4)), data-sharded, and
    # element-for-element equal to the saved values on the true S prefix
    # (padding is inert by construction — zero grads meet zero params).
    old_host = jax.device_get(state)
    new_host = jax.device_get(restored)
    assert zero.stacked_rows(new_host.opt_state, new_host.params) == 4
    # map_slots pairs each slot with its param (None for step counters);
    # old and new opt states share a treedef, so the flatten orders zip.
    new_pairs = []
    zero.map_slots(lambda s, p: new_pairs.append((s, p)),
                   new_host.opt_state, new_host.params)
    old_leaves = [leaf for _, leaf in
                  jax.tree_util.tree_flatten_with_path(old_host.opt_state)[0]]
    assert len(old_leaves) == len(new_pairs)
    refolded = 0
    for (new_slot, param), old_slot in zip(new_pairs, old_leaves):
        if param is None or getattr(old_slot, "ndim", 0) != 2:
            np.testing.assert_array_equal(
                np.asarray(new_slot), np.asarray(old_slot))
            continue
        size = int(np.prod(param.shape)) if param.shape else 1
        assert new_slot.shape == (4, -(-size // 4)), new_slot.shape
        np.testing.assert_array_equal(
            np.asarray(new_slot).reshape(-1)[:size],
            np.asarray(old_slot).reshape(-1)[:size])
        refolded += 1
    assert refolded >= 10, "adam mu+nu slots should all be refolded"


def test_zero_toggle_across_resume_is_rejected(devices, tmp_path):
    # Saved ZeRO-stacked, restored replicated (same adam optimizer, same
    # mesh): the slot trees are shape-incompatible and the failure must
    # name the knob instead of surfacing an orbax tree error.
    cfg, mesh, _, _, state = _lenet_state_zero(devices, 8)
    _save(cfg, mesh, state, str(tmp_path / "ck"))
    cfg_b = load_config(base={
        "name": "reshard-lenet-zero-off",
        "mesh": {"data": 8},
        "model": {"name": "lenet5", "num_classes": 10, "dtype": "float32"},
        "data": {"name": "synthetic_images", "global_batch_size": 64,
                 "image_size": 28, "channels": 1},
        "optimizer": {"name": "adam", "learning_rate": 0.01},
        "train": {"total_steps": 4, "spmd_mode": "shard_map"},
    })
    mesh_b = create_mesh(cfg_b.mesh)
    builder_b = StepBuilder(cfg_b, mesh_b)
    batch_b = to_global(next(get_dataset(cfg_b.data)), mesh_b)
    cfg_b.checkpoint.directory = str(tmp_path / "ck")
    cfg_b.checkpoint.async_save = False
    mgr = CheckpointManager(cfg_b.checkpoint)
    with pytest.raises(ValueError, match="zero_sharding"):
        mgr.restore(builder_b.init_state(0, batch_b))
    mgr.close()


# -- cross-mesh parity matrix on genuinely sharded states -----------------
@pytest.mark.slow
class TestCrossMeshParityMatrix:
    """ISSUE 6 satellite: {data:8} -> {data:4}, {data:8} -> {fsdp:2,pipe:4},
    {fsdp:4,data:2} -> {data:8} — per-leaf bit-exact params AND opt state
    after gather."""

    def _bert_state(self, devices, mesh_axes, *, seed=0):
        n = 1
        for v in mesh_axes.values():
            n *= v
        cfg = load_config(base={
            "name": "reshard-bert",
            "mesh": mesh_axes,
            # No pipeline_stages: pipelining restructures the param tree
            # (stacked pipeline_layers) and requires stages == pipe size,
            # so a pipelined model cannot exist on both sides of a pipe
            # resize — the {fsdp:2, pipe:4} target is a mesh-SHAPE change
            # (params fsdp-sharded, replicated over the pipe axis).
            "model": {"name": "bert", "vocab_size": 64, "hidden_size": 32,
                      "num_layers": 4, "num_heads": 2, "mlp_dim": 64,
                      "max_seq_len": 16, "dtype": "float32"},
            "data": {"name": "synthetic_mlm", "vocab_size": 64,
                     "global_batch_size": 16, "seq_len": 16},
            "optimizer": {"name": "adamw", "learning_rate": 1e-3},
            "train": {"total_steps": 2},
        })
        mesh = create_mesh(cfg.mesh, devices=devices[:n])
        builder = StepBuilder(cfg, mesh)
        batch = to_global(next(get_dataset(cfg.data)), mesh)
        state = builder.init_state(seed, batch)
        return cfg, mesh, state

    def _reshard_roundtrip(self, devices, tmp_path, axes_a, axes_b):
        cfg_a, mesh_a, state = self._bert_state(devices, axes_a)
        _save(cfg_a, mesh_a, state, str(tmp_path / "ck"))
        cfg_b, mesh_b, template = self._bert_state(devices, axes_b, seed=7)
        cfg_b.checkpoint.directory = str(tmp_path / "ck")
        cfg_b.checkpoint.async_save = False
        cfg_b.checkpoint.allow_reshard = True
        mgr = CheckpointManager(cfg_b.checkpoint, mesh=mesh_b)
        restored = mgr.restore(template)
        mgr.close()
        assert restored is not None
        _assert_trees_equal(state.params, restored.params)
        _assert_trees_equal(state.opt_state, restored.opt_state)
        return restored

    def test_data8_to_data4(self, devices, tmp_path):
        self._reshard_roundtrip(
            devices, tmp_path, {"data": 8}, {"data": 4})

    def test_data8_to_fsdp2_pipe4(self, devices, tmp_path):
        # StepBuilder refuses mesh.pipe>1 without a pipelined model, and
        # pipelining restructures the param tree — so the {fsdp:2, pipe:4}
        # template is built by hand: host snapshot re-placed with specs
        # from infer_param_specs against mesh B. That is exactly the
        # host-side respecification contract reshard.py documents.
        from jax.sharding import NamedSharding, PartitionSpec as P

        from distributed_tensorflow_framework_tpu.core.config import (
            MeshConfig,
        )
        from distributed_tensorflow_framework_tpu.parallel.sharding import (
            infer_param_specs,
        )

        cfg_a, mesh_a, state = self._bert_state(devices, {"data": 8})
        _save(cfg_a, mesh_a, state, str(tmp_path / "ck"))
        mesh_b = create_mesh(
            MeshConfig(data=1, fsdp=2, pipe=4), devices=devices)
        host = jax.device_get(state)

        def _zero(h):  # typed PRNG-key leaves cannot become numpy zeros
            if jax.dtypes.issubdtype(
                    getattr(h, "dtype", np.float32), jax.dtypes.prng_key):
                return h
            return np.zeros_like(h)

        zeroed = jax.tree.map(_zero, host)
        rep = NamedSharding(mesh_b, P())
        template = jax.tree.map(lambda h: jax.device_put(h, rep), zeroed)
        specs = jax.tree.leaves(
            infer_param_specs(host.params, mesh_b),
            is_leaf=lambda x: isinstance(x, P))
        p_leaves, p_def = jax.tree_util.tree_flatten(zeroed.params)
        template = template.replace(params=jax.tree_util.tree_unflatten(
            p_def, [jax.device_put(h, NamedSharding(mesh_b, s))
                    for h, s in zip(p_leaves, specs)]))
        cfg_a.checkpoint.allow_reshard = True
        mgr = CheckpointManager(cfg_a.checkpoint, mesh=mesh_b)
        restored = mgr.restore(template)
        mgr.close()
        assert restored is not None
        _assert_trees_equal(state.params, restored.params)
        _assert_trees_equal(state.opt_state, restored.opt_state)
        leaves = jax.tree.leaves(restored.params)
        assert dict(leaves[0].sharding.mesh.shape) == {
            "data": 1, "fsdp": 2, "expert": 1, "pipe": 4, "seq": 1,
            "model": 1}
        assert any("fsdp" in str(leaf.sharding.spec) for leaf in leaves)

    def test_fsdp4_data2_to_data8(self, devices, tmp_path):
        self._reshard_roundtrip(
            devices, tmp_path, {"fsdp": 4, "data": 2}, {"data": 8})
