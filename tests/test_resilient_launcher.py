"""scripts/train_resilient.py: bounded relaunch around a failing command.

The recovery contract it wraps (auto-restore + exact resume) is tested
end-to-end elsewhere (test_fault_tolerance.py, the RESULTS.md MoE run);
these tests pin the wrapper's own loop semantics with cheap commands.
"""

import subprocess
import sys

import pytest

SCRIPT = "scripts/train_resilient.py"


def run(args, env_extra=None):
    import os

    env = dict(os.environ)
    env.update(env_extra or {})
    return subprocess.run(
        [sys.executable, SCRIPT, *args], env=env,
        capture_output=True, text=True, timeout=120,
    )


def test_succeeds_first_try(tmp_path):
    r = run(["--max-attempts", "3", "--",
             sys.executable, "-c", "print('ok')"])
    assert r.returncode == 0
    assert "done (attempt 1)" in r.stderr


def test_retries_until_success(tmp_path):
    # Fails twice (no state file yet, then one marker), succeeds third.
    marker = tmp_path / "tries"
    prog = (
        "import pathlib, sys; p = pathlib.Path(r'%s'); "
        "n = int(p.read_text()) if p.exists() else 0; "
        "p.write_text(str(n + 1)); sys.exit(0 if n >= 2 else 1)" % marker
    )
    r = run(["--max-attempts", "5", "--retry-sleep", "0.1", "--",
             sys.executable, "-c", prog])
    assert r.returncode == 0
    assert "done (attempt 3)" in r.stderr
    assert marker.read_text() == "3"


def test_exhaustion_propagates_rc():
    r = run(["--max-attempts", "2", "--retry-sleep", "0.1", "--",
             sys.executable, "-c", "import sys; sys.exit(7)"])
    assert r.returncode == 7
    assert "attempt 2 exited rc=7" in r.stderr


def test_checkpoint_warning():
    r = run(["--max-attempts", "1", "--",
             sys.executable, "-c", "print('x')"])
    assert "no checkpoint.directory" in r.stderr
    r2 = run(["--max-attempts", "1", "--",
              sys.executable, "-c", "print('x')",
              "--set", "checkpoint.directory=/tmp/ck"])
    assert "no checkpoint.directory" not in r2.stderr


def test_cpu_fast_fail_flags_env():
    from distributed_tensorflow_framework_tpu.core.platform import (
        xla_flag_supported,
    )
    from scripts.train_resilient import build_env

    env = build_env({"JAX_PLATFORMS": "cpu", "XLA_FLAGS": ""})
    if xla_flag_supported("xla_cpu_collective_call_terminate_timeout_seconds"):
        assert "terminate_timeout_seconds=240" in env["XLA_FLAGS"]
    else:
        # This jaxlib's XLA doesn't register the flag; injecting it would
        # hard-abort every child at backend init, so it must be absent.
        assert "terminate_timeout_seconds" not in env["XLA_FLAGS"]
    # user-set value wins (and must survive even when unsupported-by-probe:
    # explicit user flags are never stripped)
    env = build_env({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_cpu_collective_call_terminate_timeout_seconds=9",
    })
    assert env["XLA_FLAGS"].count("terminate_timeout_seconds") == 1
    # non-CPU platform untouched
    env = build_env({"JAX_PLATFORMS": "tpu", "XLA_FLAGS": "abc"})
    assert env["XLA_FLAGS"] == "abc"


def test_empty_checkpoint_dir_still_warns():
    # `checkpoint.directory=` (explicitly empty → checkpointing OFF) must
    # still warn: relaunches would restart from step 0.
    r = run(["--max-attempts", "1", "--",
             sys.executable, "-c", "print('x')",
             "--set", "checkpoint.directory="])
    assert "no checkpoint.directory" in r.stderr


def test_signal_death_maps_to_shell_convention():
    # The designed failure mode: XLA's terminate timeout SIGABRTs the
    # child (returncode -6) — the wrapper must report 134 (128+SIGABRT).
    r = run(["--max-attempts", "1", "--",
             sys.executable, "-c",
             "import os, signal; os.kill(os.getpid(), signal.SIGABRT)"])
    assert r.returncode == 134, r.returncode
    assert "exited rc=134" in r.stderr


def test_config_yaml_suppresses_checkpoint_warning():
    # A --config may set checkpoint.directory in YAML — don't cry wolf.
    r = run(["--max-attempts", "1", "--",
             sys.executable, "-c", "print('x')",
             "--config", "configs/bert_base_mlm.yaml"])
    assert "no checkpoint.directory" not in r.stderr


def test_config_yaml_without_checkpoint_dir_warns(tmp_path):
    # A user YAML with checkpointing disabled must NOT suppress the
    # warning — the launcher parses the YAML instead of assuming any
    # --config enables checkpointing (ADVICE r4).
    cfg = tmp_path / "no_ckpt.yaml"
    cfg.write_text("model:\n  name: lenet5\ncheckpoint:\n  directory: ''\n")
    r = run(["--max-attempts", "1", "--",
             sys.executable, "-c", "print('x')",
             "--config", str(cfg)])
    assert "no checkpoint.directory" in r.stderr
    # An unreadable --config keeps the benefit of the doubt (the trainer
    # itself fails loudly on it).
    r2 = run(["--max-attempts", "1", "--",
              sys.executable, "-c", "print('x')",
              "--config", str(tmp_path / "missing.yaml")])
    assert "no checkpoint.directory" not in r2.stderr


def test_cancellation_not_retried():
    r = run(["--max-attempts", "5", "--retry-sleep", "0.1", "--",
             sys.executable, "-c",
             "import os, signal; os.kill(os.getpid(), signal.SIGTERM)"])
    assert r.returncode == 143
    assert "cancelled" in r.stderr
    assert "attempt 2" not in r.stderr
