"""core/roofline.py — the shared roofline predictor (bench + autotuner).

The fixture is PERF_NOTES.md round 2, the last good chip measurement
(BENCH_r02): ResNet-50 on one TPU v5 lite at 6.26 TFLOP/step, measured
arithmetic intensity 78.7 FLOP/byte against the v5e ridge of
197e12 / 819e9 ≈ 240.5 — firmly hbm_bandwidth-bound at MFU 0.31. The
predictor factored out of bench.py must reproduce exactly that verdict,
and the bench's row annotator (moved here too) must keep producing the
same fields it did before the refactor.
"""

import math

import pytest

from distributed_tensorflow_framework_tpu.core import roofline

# PERF_NOTES.md round 2 / BENCH_r02: the measured ResNet-50 step.
R02_CHIP = "TPU v5 lite"
R02_FLOPS_PER_STEP = 6.26e12
R02_INTENSITY = 78.7
R02_BYTES_PER_STEP = R02_FLOPS_PER_STEP / R02_INTENSITY
V5E_PEAK_FLOPS, V5E_HBM_BW, _ = roofline.CHIP_PEAKS["TPU v5e"]


class TestRidgePoint:
    def test_v5e_ridge_is_240(self):
        ridge, source = roofline.ridge_point(R02_CHIP)
        assert source == R02_CHIP
        assert ridge == pytest.approx(240.5, abs=0.1)
        assert ridge == pytest.approx(V5E_PEAK_FLOPS / V5E_HBM_BW)

    def test_unknown_chip_falls_back_to_v5e_reference(self):
        ridge, source = roofline.ridge_point("cpu")
        assert source == roofline.RIDGE_FALLBACK_CHIP
        assert ridge == pytest.approx(240.5, abs=0.1)

    def test_aliases_agree(self):
        # v5e is listed under both its device_kind and marketing names.
        assert (roofline.CHIP_PEAKS["TPU v5 lite"]
                == roofline.CHIP_PEAKS["TPU v5e"])
        assert (roofline.CHIP_PEAKS["TPU v6 lite"]
                == roofline.CHIP_PEAKS["TPU v6e"])


class TestChipHbmCapacity:
    def test_known_chip_uses_spec_sheet(self):
        assert roofline.chip_hbm_capacity("TPU v4") == 32 * roofline.GIB

    def test_unknown_chip_falls_back_to_host_ram(self):
        cap = roofline.chip_hbm_capacity("cpu")
        # Host RAM: positive and at least tens of MiB on any real box.
        assert cap is None or cap > 64 * 1024 * 1024


class TestTrafficBytes:
    def test_footprint_plus_wire_plus_opt(self):
        analysis = {"argument_bytes": 100, "output_bytes": 10,
                    "temp_bytes": 5, "generated_code_bytes": 999}
        # generated_code_bytes is NOT streamed per step — excluded.
        assert roofline.traffic_bytes(analysis, 7, 3) == 125.0

    def test_tolerates_missing_pieces(self):
        assert roofline.traffic_bytes(None) == 0.0
        assert roofline.traffic_bytes({"argument_bytes": None}, 5) == 5.0


class TestPredict:
    def test_r02_fixture_is_hbm_bound(self):
        p = roofline.predict(R02_CHIP, R02_FLOPS_PER_STEP,
                             R02_BYTES_PER_STEP)
        assert p.bound == "hbm_bandwidth"
        assert p.intensity == pytest.approx(78.7)
        assert p.ridge == pytest.approx(240.5, abs=0.1)
        assert p.ridge_source == R02_CHIP  # measured chip, no fallback tag
        # HBM term binds: bytes/bw > flops/peak.
        assert p.sec_per_step == p.sec_hbm > p.sec_compute
        assert p.sec_hbm == pytest.approx(R02_BYTES_PER_STEP / V5E_HBM_BW)

    def test_r02_floor_implies_mfu_ceiling_near_measured(self):
        # The analytic floor's implied MFU ceiling: intensity/ridge =
        # 78.7/240.5 ≈ 0.327. BENCH_r02 measured MFU 0.31 at 94% HBM BW
        # util — the measurement sits just under the model's ceiling,
        # which is exactly what a sound lower-bound model must allow.
        p = roofline.predict(R02_CHIP, R02_FLOPS_PER_STEP,
                             R02_BYTES_PER_STEP)
        mfu_ceiling = (R02_FLOPS_PER_STEP / p.sec_per_step) / V5E_PEAK_FLOPS
        assert mfu_ceiling == pytest.approx(78.7 / 240.5, rel=1e-3)
        assert 0.31 <= mfu_ceiling < 0.35

    def test_compute_bound_above_ridge(self):
        p = roofline.predict("TPU v5e", 1e15, 1e12)  # intensity 1000
        assert p.bound == "compute"
        assert p.sec_per_step == p.sec_compute

    def test_unknown_chip_tagged_fallback(self):
        p = roofline.predict("cpu", 1e12, 1e11)
        assert p.ridge_source == "TPU v5e (fallback)"
        assert p.bound == "hbm_bandwidth"  # intensity 10 < 240

    def test_n_chips_divides_work(self):
        one = roofline.predict(R02_CHIP, R02_FLOPS_PER_STEP,
                               R02_BYTES_PER_STEP, n_chips=1)
        four = roofline.predict(R02_CHIP, R02_FLOPS_PER_STEP,
                                R02_BYTES_PER_STEP, n_chips=4)
        assert four.sec_per_step == pytest.approx(one.sec_per_step / 4)
        assert four.bound == one.bound  # intensity is per-program

    def test_zero_bytes_is_compute_bound(self):
        p = roofline.predict(R02_CHIP, 1e12, 0.0)
        assert p.intensity is None
        assert p.bound == "compute"
        assert math.isfinite(p.sec_per_step)


class TestAnnotateRoofline:
    """The bench row annotator, post-refactor parity."""

    def _r02_result(self):
        # sec_per_step chosen so achieved TFLOP/s ≈ the measured 61.2
        # (MFU 0.311) — BENCH_r02's actual shape.
        sec = R02_FLOPS_PER_STEP / 61.2e12
        return {
            "flops_per_step": R02_FLOPS_PER_STEP,
            "bytes_per_step": R02_BYTES_PER_STEP,
            "sec_per_step": sec,
        }

    def test_r02_row_fields(self):
        out = {}
        roofline.annotate_roofline(out, self._r02_result(), R02_CHIP, 1)
        assert out["tflops_per_sec"] == pytest.approx(61.2, abs=0.01)
        assert out["arith_intensity"] == pytest.approx(78.7)
        assert out["bound"] == "hbm_bandwidth"
        assert out["mfu"] == pytest.approx(61.2 / 197.0, abs=1e-3)
        assert 0.9 < out["hbm_bw_util"] <= 1.0
        assert "bound_ridge_source" not in out  # known chip, no fallback

    def test_bench_reexports_the_shared_model(self):
        # bench.py must serve the same names it always exported, now
        # re-exported from core/roofline so tuner and bench share one
        # ridge.
        import bench

        assert bench.CHIP_PEAKS is roofline.CHIP_PEAKS
        assert bench.GIB == roofline.GIB
        assert bench.chip_hbm_capacity is roofline.chip_hbm_capacity
        assert bench._annotate_roofline is roofline.annotate_roofline

    def test_unknown_chip_gets_fallback_verdict(self):
        out = {}
        roofline.annotate_roofline(out, self._r02_result(), "cpu", 1)
        assert out["bound"] == "hbm_bandwidth"
        assert out["bound_ridge_source"] == "TPU v5e (fallback)"
        assert "mfu" not in out  # no peak table entry for cpu

    def test_no_flops_no_annotation(self):
        out = {}
        roofline.annotate_roofline(
            out, {"flops_per_step": 0, "bytes_per_step": 0,
                  "sec_per_step": 1.0}, R02_CHIP, 1)
        assert out == {}

    def test_accum_scaled_tag(self):
        out = {}
        roofline.annotate_roofline(out, self._r02_result(), R02_CHIP, 1,
                                   accum_scaled=True)
        assert out["roofline_bound"] == "accum-scaled-upper"
