"""Space-to-depth ResNet stem (models/resnet.py space_to_depth_stem).

Proves the s2d stem is a reparametrization of the standard 7×7/s2 SAME
conv, not an approximation: zero-pad the 7×7×3 kernel to 8×8×3
(bottom/right), regroup into 4×4×12, and the 4×4/s1 conv with padding
((1,2),(1,2)) on the space-to-depth input reproduces the original
output numerically (tested to rtol 1e-6 / atol 1e-5 — reassociated
matmul accumulation means the TPU results are not literally
bit-identical). Note the 45 zero-padded kernel positions are trainable,
so the trained function class is a strict superset of the 7×7 stem's.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_framework_tpu.models.layers import space_to_depth


def _s2d_kernel(w7: np.ndarray) -> np.ndarray:
    """Map a (7,7,C,F) HWIO kernel to the (4,4,4C,F) s2d-equivalent."""
    k, _, c, f = w7.shape
    assert k == 7
    w8 = np.zeros((8, 8, c, f), w7.dtype)
    w8[:7, :7] = w7
    # Output channel order of space_to_depth is (di, dj, c) flattened.
    ws2d = np.zeros((4, 4, 4 * c, f), w7.dtype)
    for a in range(4):
        for e in range(4):
            for bi in range(2):
                for bj in range(2):
                    ws2d[a, e, (bi * 2 + bj) * c:(bi * 2 + bj) * c + c] = (
                        w8[2 * a + bi, 2 * e + bj]
                    )
    return ws2d


def test_s2d_conv_exactly_reproduces_conv7x7_s2():
    rng = np.random.default_rng(0)
    x = rng.standard_normal((2, 32, 32, 3)).astype(np.float32)
    w7 = rng.standard_normal((7, 7, 3, 16)).astype(np.float32)

    ref = jax.lax.conv_general_dilated(
        jnp.asarray(x), jnp.asarray(w7), window_strides=(2, 2),
        padding="SAME", dimension_numbers=("NHWC", "HWIO", "NHWC"))
    got = jax.lax.conv_general_dilated(
        space_to_depth(jnp.asarray(x), 2), jnp.asarray(_s2d_kernel(w7)),
        window_strides=(1, 1), padding=((1, 2), (1, 2)),
        dimension_numbers=("NHWC", "HWIO", "NHWC"))

    assert ref.shape == got.shape == (2, 16, 16, 16)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(got),
                               rtol=1e-6, atol=1e-5)


def test_space_to_depth_layout():
    x = np.arange(2 * 4 * 4 * 3, dtype=np.float32).reshape(2, 4, 4, 3)
    y = np.asarray(space_to_depth(jnp.asarray(x), 2))
    assert y.shape == (2, 2, 2, 12)
    # channel (di*2+dj)*3 + c holds pixel (2i+di, 2j+dj, c)
    for di in range(2):
        for dj in range(2):
            for c in range(3):
                np.testing.assert_array_equal(
                    y[:, :, :, (di * 2 + dj) * 3 + c],
                    x[:, di::2, dj::2, c])
    with pytest.raises(ValueError):
        space_to_depth(jnp.zeros((1, 5, 4, 3)), 2)


def test_s2d_resnet_forward_and_step():
    from distributed_tensorflow_framework_tpu.models.resnet import make_resnet

    model = make_resnet(18, num_classes=10, dtype=jnp.float32,
                        space_to_depth_stem=True)
    x = jnp.ones((2, 64, 64, 3), jnp.float32)
    variables = model.init(jax.random.key(0), x, train=False)
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (2, 10)
    # Stem kernel is the regrouped 4×4×12 shape.
    assert variables["params"]["stem_s2d"]["conv"]["kernel"].shape == (
        4, 4, 12, 64)
    # Same spatial pyramid as the conv7 stem on the same input.
    ref = make_resnet(18, num_classes=10, dtype=jnp.float32)
    ref_vars = ref.init(jax.random.key(0), x, train=False)
    assert ref.apply(ref_vars, x, train=False).shape == logits.shape


def test_s2d_rejected_for_non_resnet_and_cifar_stem():
    from distributed_tensorflow_framework_tpu.core.config import ModelConfig
    from distributed_tensorflow_framework_tpu.models import get_model
    from distributed_tensorflow_framework_tpu.models.resnet import make_resnet

    with pytest.raises(ValueError):
        get_model(ModelConfig(name="lenet5", space_to_depth_stem=True))
    with pytest.raises(ValueError):
        make_resnet(50, cifar_stem=True, space_to_depth_stem=True)
