"""LR schedule tests."""

import numpy as np

from distributed_tensorflow_framework_tpu.core.config import OptimizerConfig
from distributed_tensorflow_framework_tpu.train.schedules import make_schedule


def test_warmup_then_staircase_boundaries_absolute():
    cfg = OptimizerConfig(
        name="sgd_momentum",
        learning_rate=1.0,
        warmup_steps=100,
        schedule="staircase",
        boundaries=[200, 300],
        decay_factor=0.1,
    )
    sched = make_schedule(cfg, total_steps=400)
    np.testing.assert_allclose(float(sched(0)), 0.0)
    np.testing.assert_allclose(float(sched(50)), 0.5)
    np.testing.assert_allclose(float(sched(100)), 1.0)
    # Boundaries are absolute global steps: first drop AT step 200.
    np.testing.assert_allclose(float(sched(199)), 1.0)
    np.testing.assert_allclose(float(sched(201)), 0.1, rtol=1e-6)
    np.testing.assert_allclose(float(sched(301)), 0.01, rtol=1e-6)


def test_cosine_with_warmup():
    cfg = OptimizerConfig(learning_rate=2.0, warmup_steps=10, schedule="cosine")
    sched = make_schedule(cfg, total_steps=110)
    np.testing.assert_allclose(float(sched(10)), 2.0)
    assert float(sched(60)) < 2.0
    np.testing.assert_allclose(float(sched(110)), 0.0, atol=1e-6)
