"""Schema-drift guard: every KIND_* round-trips through the ONE schema.

The telemetry contract (docs/OBSERVABILITY.md) is a single versioned
record shape shared by every emitter — train loop, bench, supervisor,
serve, goodput ledger, memory monitor. This guard makes drift a test
failure instead of a post-mortem surprise:

  * every ``KIND_*`` constant builds a valid event via ``make_event``
    and survives JSON + ``TelemetryWriter`` → ``read_events(strict=True)``
    round trips;
  * the reserved top-level field set is pinned — adding a field without
    bumping the schema version fails HERE, forcing the conscious choice
    the RESERVED_FIELDS comment asks for;
  * unknown top-level fields and mistyped sections are rejected.
"""

import json

import pytest

from distributed_tensorflow_framework_tpu.core import telemetry


def _all_kinds() -> list[str]:
    kinds = sorted(
        getattr(telemetry, name)
        for name in dir(telemetry) if name.startswith("KIND_"))
    assert len(kinds) >= 25, kinds  # self-check: extraction saw them all
    return kinds


# Kind-shaped payloads: every event gets the common sections plus an
# extra payload with the nested dicts the new kinds actually carry
# (goodput buckets, memory analysis) — nesting must survive _to_scalar.
def _payload(kind: str) -> dict:
    return {
        "step": 7,
        "metrics": {"value": 1.5, "wall_s": 10.0},
        "health": {"event": "guard"},
        "buckets": {"step_compute": 8.0, "other": 2.0},
        "analysis": {"argument_bytes": 10, "nested": {"deep": 1}},
        "source": "guard",
    }


@pytest.mark.parametrize("kind", _all_kinds())
def test_every_kind_round_trips_make_validate(kind):
    ev = telemetry.make_event(kind, run_id="guard", **_payload(kind))
    assert telemetry.validate_event(ev) == []
    # The JSON wire trip must preserve validity AND the nested extras.
    ev2 = json.loads(json.dumps(ev, default=str))
    assert telemetry.validate_event(ev2) == []
    assert ev2["kind"] == kind
    assert ev2["extra"]["buckets"] == {"step_compute": 8.0, "other": 2.0}
    assert ev2["extra"]["analysis"]["nested"] == {"deep": 1}


def test_every_kind_survives_writer_strict_read(tmp_path):
    path = str(tmp_path / "events.jsonl")
    w = telemetry.TelemetryWriter(path, run_id="guard")
    for kind in _all_kinds():
        w.emit(kind, **_payload(kind))
    w.close()
    seen = [ev["kind"] for ev in telemetry.read_events(path, strict=True)]
    assert seen == _all_kinds()


def test_reserved_fields_are_pinned():
    """Changing the top-level shape must be a conscious schema decision:
    update this tuple AND (for additions readers depend on) the schema
    version, not just RESERVED_FIELDS."""
    assert telemetry.RESERVED_FIELDS == (
        "schema", "run_id", "kind", "t", "step", "metrics", "phases",
        "throughput", "roofline", "collectives", "health", "extra")
    assert telemetry.SCHEMA == "dtf-telemetry/1"


def test_unknown_top_level_field_rejected():
    ev = telemetry.make_event(telemetry.KIND_GOODPUT, run_id="guard")
    ev["surprise"] = 1
    errors = telemetry.validate_event(ev)
    assert any("surprise" in e for e in errors), errors


def test_mistyped_section_rejected():
    ev = telemetry.make_event(telemetry.KIND_MEMORY, run_id="guard")
    ev["metrics"] = "not-a-mapping"
    errors = telemetry.validate_event(ev)
    assert any("metrics" in e for e in errors), errors
