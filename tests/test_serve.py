"""Serving path (serve/): export round-trip, batcher behavior, padding
buckets, reshard gate UX, and the KIND_SERVE_* telemetry rollups.

The slow end-to-end drill (real HTTP server subprocess + load generator
+ SIGTERM drain) lives in test_serve_drill.py; this file stays in tier 1
by driving the engine in-process.
"""

import copy
import json
import os
import threading

import jax
import numpy as np
import pytest
from test_train_lenet import lenet_config
from test_train_models import tiny_bert_base

from distributed_tensorflow_framework_tpu.ckpt import manifest as mf
from distributed_tensorflow_framework_tpu.ckpt.reshard import (
    MeshTopologyError,
)
from distributed_tensorflow_framework_tpu.core import telemetry
from distributed_tensorflow_framework_tpu.core.config import load_config
from distributed_tensorflow_framework_tpu.models import get_model
from distributed_tensorflow_framework_tpu.serve import (
    InferenceEngine,
    OversizeRequestError,
    SequenceTooLongError,
    export_checkpoint,
    load_artifact,
    save_artifact,
    serving_mesh,
)
from distributed_tensorflow_framework_tpu.serve.engine import (
    batch_buckets,
    pick_bucket,
)
from distributed_tensorflow_framework_tpu.serve.export import (
    ARTIFACT_JSON,
    input_spec_for,
)
from distributed_tensorflow_framework_tpu.train import Trainer

pytestmark = pytest.mark.serve


def _serve_overrides(**extra):
    base = {
        "serve.data": 1,
        "serve.max_batch_size": 8,
        "serve.max_wait_ms": 5.0,
        "serve.report_interval_s": 60.0,
    }
    base.update(extra)
    return base


@pytest.fixture(scope="module")
def trained_cfg(tmp_path_factory, devices):
    """A short lenet training run with a committed sync checkpoint,
    trained on the default 8-device data mesh (so exporting onto the
    1-device serving mesh is a REAL topology change)."""
    ckpt_dir = tmp_path_factory.mktemp("serve_ckpt")
    cfg = lenet_config(**{
        "checkpoint.directory": str(ckpt_dir),
        "checkpoint.async_save": False,
        "checkpoint.save_interval_steps": 10,
        "train.total_steps": 10,
    })
    trainer = Trainer(cfg)
    trainer.build()
    trainer.train()
    return cfg


@pytest.fixture(scope="module")
def artifact_dir(trained_cfg, tmp_path_factory):
    cfg = copy.deepcopy(trained_cfg)
    for k, v in _serve_overrides(**{"serve.allow_reshard": True}).items():
        obj = cfg
        parts = k.split(".")
        for p in parts[:-1]:
            obj = getattr(obj, p)
        setattr(obj, parts[-1], v)
    out = tmp_path_factory.mktemp("serve_artifact") / "lenet"
    return export_checkpoint(cfg, str(out))


@pytest.fixture(scope="module")
def artifact(artifact_dir):
    return load_artifact(artifact_dir)


@pytest.fixture(scope="module")
def engine(artifact, trained_cfg):
    cfg = copy.deepcopy(trained_cfg)
    for k, v in _serve_overrides().items():
        obj = cfg
        parts = k.split(".")
        for p in parts[:-1]:
            obj = getattr(obj, p)
        setattr(obj, parts[-1], v)
    eng = InferenceEngine(artifact, cfg.serve, mesh=serving_mesh(1))
    yield eng
    eng.drain(10.0)


def _direct_logits(artifact, images):
    model = get_model(artifact.model_config)
    variables = {"params": artifact.params}
    if jax.tree.leaves(artifact.batch_stats):
        variables["batch_stats"] = artifact.batch_stats
    return np.asarray(model.apply(variables, images, train=False))


# ----------------------------------------------------------- pure helpers


def test_pick_bucket_boundaries():
    assert pick_bucket(1, [8, 16]) == 8
    assert pick_bucket(8, [8, 16]) == 8  # boundary lands in the bucket
    assert pick_bucket(9, [8, 16]) == 16
    assert pick_bucket(16, [8, 16]) == 16
    with pytest.raises(ValueError):
        pick_bucket(17, [8, 16])


def test_batch_buckets_ladder():
    assert batch_buckets(8, 1) == [1, 2, 4, 8]
    assert batch_buckets(1, 1) == [1]
    assert batch_buckets(12, 2) == [2, 4, 8, 12]
    # Cap rounds UP to a dp multiple so the padded batch always shards.
    assert batch_buckets(7, 2) == [2, 4, 8]


# ------------------------------------------------------- export round-trip


def test_export_artifact_layout(artifact_dir, artifact):
    meta_path = os.path.join(artifact_dir, ARTIFACT_JSON)
    assert os.path.isfile(meta_path)
    with open(meta_path) as fh:
        meta = json.load(fh)
    assert meta["schema"] == "dtf-serve-artifact/1"
    assert meta["task"] == "classification"
    assert meta["step"] == 10
    assert meta["model"]["name"] == "lenet5"
    assert meta["source"]["serve_mesh"]["data"] == 1
    # Integrity manifest commits the whole directory (ckpt discipline).
    manifest = mf.read_manifest(artifact_dir)
    assert manifest is not None
    assert mf.verify_step_dir(artifact_dir, manifest) == []
    # Round-trip: digest recomputed at load matches the recorded one.
    assert artifact.param_spec_digest == meta["param_spec_digest"]
    assert artifact.step == 10
    assert "image" in artifact.input_spec


def test_export_refuses_nonempty_dir(artifact_dir, artifact, trained_cfg):
    with pytest.raises(ValueError, match="immutable"):
        save_artifact(
            artifact_dir,
            model_config=artifact.model_config, task=artifact.task,
            params=artifact.params, batch_stats=artifact.batch_stats,
            step=1, input_spec=artifact.input_spec)


def test_reshard_gate_names_serve_knob(trained_cfg, tmp_path):
    """Without serve.allow_reshard, exporting a training-mesh checkpoint
    must fail with the TYPED error whose hint names the SERVE-side knob
    (not just checkpoint.allow_reshard, which is the wrong config block
    for an inference operator)."""
    cfg = copy.deepcopy(trained_cfg)
    cfg.serve.data = 1
    assert cfg.serve.allow_reshard is False
    with pytest.raises(MeshTopologyError) as ei:
        export_checkpoint(cfg, str(tmp_path / "gated"))
    assert "serve.allow_reshard" in str(ei.value)
    assert ei.value.hint and "serve.allow_reshard" in ei.value.hint
    assert not os.path.exists(tmp_path / "gated")


def test_load_artifact_rejects_tampering(artifact_dir, tmp_path):
    import shutil

    tampered = tmp_path / "tampered"
    shutil.copytree(artifact_dir, tampered)
    meta_path = tampered / ARTIFACT_JSON
    meta = json.loads(meta_path.read_text())
    meta["step"] = 999  # payload no longer matches the manifest hash
    meta_path.write_text(json.dumps(meta))
    with pytest.raises(ValueError, match="integrity"):
        load_artifact(str(tampered))


# ---------------------------------------------------------------- engine


def test_predict_matches_direct_forward(engine, artifact):
    rng = np.random.default_rng(0)
    images = rng.normal(size=(3, 28, 28, 1)).astype(np.float32)
    served = engine.predict({"image": images}, timeout=30.0)
    direct = _direct_logits(artifact, images)
    assert served.shape == direct.shape
    np.testing.assert_allclose(served, direct, rtol=1e-5, atol=1e-5)


def test_single_row_without_batch_dim(engine, artifact):
    rng = np.random.default_rng(1)
    image = rng.normal(size=(28, 28, 1)).astype(np.float32)
    served = engine.predict({"image": image}, timeout=30.0)
    assert served.shape[0] == 1
    np.testing.assert_allclose(
        served, _direct_logits(artifact, image[None]), rtol=1e-5, atol=1e-5)


def test_concurrent_batched_matches_unbatched(engine, artifact):
    """~12 concurrent requests of varied row counts: the batcher
    coalesces them into padded batches, and every caller still gets
    exactly its own rows' logits."""
    rng = np.random.default_rng(2)
    requests = [rng.normal(size=(r, 28, 28, 1)).astype(np.float32)
                for r in [1, 2, 3, 1, 4, 2, 1, 5, 2, 3, 1, 2]]
    futures = []
    barrier = threading.Barrier(len(requests))
    results = [None] * len(requests)

    def fire(i):
        barrier.wait()  # maximize queue overlap → real coalescing
        results[i] = engine.predict({"image": requests[i]}, timeout=60.0)

    threads = [threading.Thread(target=fire, args=(i,))
               for i in range(len(requests))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    del futures
    for req, served in zip(requests, results):
        assert served.shape[0] == req.shape[0]
        np.testing.assert_allclose(
            served, _direct_logits(artifact, req), rtol=1e-5, atol=1e-5)
    # Coalescing happened: fewer batches than requests (the barrier makes
    # anything else wildly unlikely with an 8-row window).
    assert engine.stats()["batches"] < engine.stats()["requests"]


def test_oversize_request_rejected(engine):
    images = np.zeros((9, 28, 28, 1), np.float32)  # max_batch_size=8
    with pytest.raises(OversizeRequestError):
        engine.submit({"image": images})


def test_bad_inputs_rejected(engine):
    from distributed_tensorflow_framework_tpu.serve import ServeError

    with pytest.raises(ServeError, match="unknown input"):
        engine.submit({"image": np.zeros((1, 28, 28, 1), np.float32),
                       "bogus": [1]})
    with pytest.raises(ServeError, match="missing required"):
        engine.submit({})
    with pytest.raises(ServeError, match="expects"):
        engine.submit({"image": np.zeros((1, 14, 14, 1), np.float32)})


def test_empty_queue_is_quiet(engine):
    """An idle engine launches no batches — the admission wait must not
    spin out empty batches when the queue times out empty."""
    import time

    before = engine.stats()["batches"]
    time.sleep(0.25)  # many max_wait_ms windows
    assert engine.stats()["batches"] == before
    # ...and it still serves afterwards.
    out = engine.predict(
        {"image": np.zeros((1, 28, 28, 1), np.float32)}, timeout=30.0)
    assert out.shape[0] == 1


# ------------------------------------------------- MLM padding buckets


@pytest.fixture(scope="module")
def bert_artifact(tmp_path_factory, devices):
    """An UNTRAINED tiny-BERT artifact via save_artifact directly —
    bucket mechanics don't need trained weights."""
    base = tiny_bert_base(max_seq_len=16)
    base["data"]["seq_len"] = 16
    base["data"]["global_batch_size"] = 8
    cfg = load_config(base=base)
    mesh = serving_mesh(1)
    from distributed_tensorflow_framework_tpu.train.step import StepBuilder

    cfg.mesh.data = 1
    builder = StepBuilder(cfg, mesh)
    sample = {
        "input_ids": np.zeros((1, 16), np.int32),
        "targets": np.full((1, 16), -1, np.int32),
        "attention_mask": np.ones((1, 16), np.int32),
    }
    state = builder.init_state(0, sample)
    out = tmp_path_factory.mktemp("bert_artifact") / "bert"
    save_artifact(
        str(out),
        model_config=cfg.model, task="mlm",
        params=jax.device_get(state.params),
        batch_stats=jax.device_get(state.batch_stats),
        step=0, input_spec=input_spec_for(cfg, "mlm"),
        vocab_size=cfg.data.vocab_size)
    return load_artifact(str(out))


@pytest.fixture(scope="module")
def bert_engine(bert_artifact):
    cfg = load_config(base={"model": {"name": "bert", "max_seq_len": 16}})
    cfg.serve.data = 1
    cfg.serve.max_batch_size = 4
    cfg.serve.max_wait_ms = 2.0
    cfg.serve.report_interval_s = 60.0
    cfg.serve.seq_buckets = [8, 16]
    eng = InferenceEngine(bert_artifact, cfg.serve, mesh=serving_mesh(1))
    yield eng
    eng.drain(10.0)


def test_seq_buckets_bound_compiles(bert_engine, bert_artifact):
    rng = np.random.default_rng(3)

    def request(seq):
        ids = rng.integers(1, 512, size=(1, seq)).astype(np.int32)
        return {"input_ids": ids, "attention_mask": np.ones_like(ids)}

    out5 = bert_engine.predict(request(5), timeout=60.0)
    assert out5.shape[:2] == (1, 5)  # seq padding stripped from the reply
    assert (8, 1) in bert_engine._compiled  # padded to the 8-bucket
    out9 = bert_engine.predict(request(9), timeout=60.0)
    assert out9.shape[:2] == (1, 9)
    assert (16, 1) in bert_engine._compiled
    # A second in-bucket length reuses the compile (no new key).
    n = len(bert_engine._compiled)
    bert_engine.predict(request(7), timeout=60.0)
    assert len(bert_engine._compiled) == n
    with pytest.raises(SequenceTooLongError):
        bert_engine.submit(request(17))


def test_mlm_padding_is_inert(bert_engine, bert_artifact):
    """Padding a 5-token request up to the 8 bucket must not perturb the
    real positions: BERT masks padded KEYS out of attention entirely."""
    rng = np.random.default_rng(4)
    ids = rng.integers(1, 512, size=(2, 5)).astype(np.int32)
    mask = np.ones_like(ids)
    served = bert_engine.predict(
        {"input_ids": ids, "attention_mask": mask}, timeout=60.0)
    model = get_model(bert_artifact.model_config)
    direct = model.apply(
        {"params": bert_artifact.params}, ids, mask, train=False)
    if isinstance(direct, dict):
        direct = direct["logits"]
    np.testing.assert_allclose(
        served, np.asarray(direct), rtol=1e-4, atol=1e-4)


# ------------------------------------------------------------- telemetry


def test_serve_telemetry_rollup(artifact, trained_cfg, tmp_path):
    """All five KIND_SERVE_* events flow end-to-end: emitted by the
    engine, schema-valid, aggregated by summarize_events, and surfaced in
    the human rollup (the analyze_trace.py summarize_run surface)."""
    import time

    cfg = copy.deepcopy(trained_cfg)
    cfg.serve.data = 1
    cfg.serve.max_batch_size = 4
    cfg.serve.max_wait_ms = 2.0
    cfg.serve.report_interval_s = 0.05  # force a KIND_SERVE_QUEUE tick
    events = str(tmp_path / "events.jsonl")
    writer = telemetry.TelemetryWriter(events)
    eng = InferenceEngine(
        artifact, cfg.serve, mesh=serving_mesh(1),
        telemetry_writer=writer)
    try:
        rng = np.random.default_rng(5)
        for rows in (1, 3, 2, 1, 4, 2):
            eng.predict(
                {"image": rng.normal(size=(rows, 28, 28, 1))
                 .astype(np.float32)}, timeout=30.0)
        time.sleep(0.15)  # at least one reporter tick
    finally:
        assert eng.drain(10.0)
        writer.close()
    kinds = {ev["kind"] for ev in telemetry.read_events(events)}
    assert telemetry.KIND_SERVE_REQUEST in kinds
    assert telemetry.KIND_SERVE_BATCH in kinds
    assert telemetry.KIND_SERVE_QUEUE in kinds
    assert telemetry.KIND_SERVE_LATENCY in kinds
    assert telemetry.KIND_SERVE_RECOMPILE in kinds
    summary = telemetry.summarize_events(events)
    serve = summary["serve"]
    assert serve["requests"] == 6
    assert serve["rows"] == 13
    assert 1 <= serve["batches"] <= 6
    assert serve["batch_rows"] == 13
    assert serve["padded_rows"] >= serve["batch_rows"]
    assert serve["latency"]["count"] == 6
    assert serve["latency"]["p99_ms"] >= serve["latency"]["p50_ms"]
    assert serve["recompiles"]  # first bucket use was recorded
    text = telemetry.format_run_summary(summary)
    assert "serving: 6 requests (13 rows)" in text
    assert "p99" in text
    assert "bucket recompiles" in text


def test_runs_without_serve_events_have_no_serving_section(tmp_path):
    events = str(tmp_path / "train_only.jsonl")
    writer = telemetry.TelemetryWriter(events)
    writer.emit(telemetry.KIND_TRAIN_STEP, step=1, metrics={"loss": 1.0})
    writer.close()
    summary = telemetry.summarize_events(events)
    assert summary["serve"] is None
    assert "serving:" not in telemetry.format_run_summary(summary)


# ------------------------------------------------------------ live reload


def _perturbed_artifact_dir(artifact, out_dir):
    """A second artifact with the SAME architecture but genuinely
    different weights — what a rolling deploy actually ships."""
    params = jax.tree.map(
        lambda x: x + np.asarray(0.1, x.dtype)
        if np.issubdtype(np.asarray(x).dtype, np.floating) else x,
        artifact.params)
    return save_artifact(
        str(out_dir),
        model_config=artifact.model_config,
        task=artifact.task,
        params=params,
        batch_stats=artifact.batch_stats,
        step=artifact.step + 1,
        input_spec=artifact.input_spec,
        vocab_size=artifact.meta.get("vocab_size"),
    )


def _fresh_engine(artifact, trained_cfg):
    cfg = copy.deepcopy(trained_cfg)
    for k, v in _serve_overrides().items():
        obj = cfg
        parts = k.split(".")
        for p in parts[:-1]:
            obj = getattr(obj, p)
        setattr(obj, parts[-1], v)
    return InferenceEngine(artifact, cfg.serve, mesh=serving_mesh(1))


def test_reload_bitwise_parity_with_cold_engine(
        artifact, artifact_dir, trained_cfg, tmp_path):
    """The acceptance bar for live reload: a reloaded engine's outputs
    are BITWISE identical to a cold-started engine on the new artifact
    (same jitted forward — model-config equality is enforced — and the
    same placement path, so parity holds by construction and is verified
    here, not assumed)."""
    new_dir = _perturbed_artifact_dir(artifact, tmp_path / "v2")
    new_artifact = load_artifact(new_dir)
    assert new_artifact.version_digest != artifact.version_digest
    rng = np.random.default_rng(11)
    images = rng.normal(size=(3, 28, 28, 1)).astype(np.float32)

    eng = _fresh_engine(artifact, trained_cfg)
    cold = _fresh_engine(new_artifact, trained_cfg)
    try:
        before = np.asarray(eng.predict({"image": images}, timeout=30.0))
        result = eng.reload(new_dir, timeout=60.0)
        assert result["from_step"] == artifact.step
        assert result["to_step"] == artifact.step + 1
        assert result["from_digest"] != result["to_digest"]
        after = np.asarray(eng.predict({"image": images}, timeout=30.0))
        cold_out = np.asarray(cold.predict({"image": images}, timeout=30.0))
        assert not np.array_equal(after, before)  # swap actually applied
        assert np.array_equal(after, cold_out), (
            "reloaded outputs diverge from a cold engine on the same "
            f"artifact by {np.max(np.abs(after - cold_out))}")
        info = eng.artifact_info()
        assert info["reloads"] == 1
        assert info["content_digest"] == new_artifact.version_digest
        assert info["step"] == artifact.step + 1
    finally:
        assert eng.drain(10.0)
        assert cold.drain(10.0)


def test_reload_rejects_tampered_artifact_and_keeps_serving(
        artifact, artifact_dir, trained_cfg, tmp_path):
    """A truncated payload fails manifest verification on the CALLING
    thread: typed ReloadError out, zero batcher involvement, and the old
    weights keep serving bit-for-bit."""
    import shutil

    from distributed_tensorflow_framework_tpu.core import faults
    from distributed_tensorflow_framework_tpu.serve import ReloadError

    tampered = tmp_path / "tampered"
    shutil.copytree(artifact_dir, tampered)
    assert faults.corrupt_checkpoint_dir(str(tampered)) is not None
    rng = np.random.default_rng(12)
    images = rng.normal(size=(2, 28, 28, 1)).astype(np.float32)
    eng = _fresh_engine(artifact, trained_cfg)
    try:
        before = np.asarray(eng.predict({"image": images}, timeout=30.0))
        with pytest.raises(ReloadError, match="still serving step"):
            eng.reload(str(tampered), timeout=60.0)
        after = np.asarray(eng.predict({"image": images}, timeout=30.0))
        assert np.array_equal(after, before)
        assert eng.artifact_info()["reloads"] == 0
        assert eng.artifact_info()["content_digest"] == \
            artifact.version_digest
    finally:
        assert eng.drain(10.0)


def test_reload_rejects_incompatible_input_spec(
        artifact, trained_cfg, tmp_path):
    from distributed_tensorflow_framework_tpu.serve import ReloadError

    wrong_spec = dict(artifact.input_spec)
    wrong_spec["image"] = {"shape": [14, 14, 1], "dtype": "float32"}
    bad_dir = save_artifact(
        str(tmp_path / "wrong_spec"),
        model_config=artifact.model_config,
        task=artifact.task,
        params=artifact.params,
        batch_stats=artifact.batch_stats,
        step=artifact.step,
        input_spec=wrong_spec,
        vocab_size=artifact.meta.get("vocab_size"),
    )
    eng = _fresh_engine(artifact, trained_cfg)
    try:
        with pytest.raises(ReloadError, match="input spec"):
            eng.reload(bad_dir, timeout=60.0)
    finally:
        assert eng.drain(10.0)


def test_reload_refused_after_drain(artifact, artifact_dir, trained_cfg):
    from distributed_tensorflow_framework_tpu.serve import (
        EngineClosedError,
    )

    eng = _fresh_engine(artifact, trained_cfg)
    assert eng.drain(10.0)
    with pytest.raises(EngineClosedError):
        eng.reload(artifact_dir, timeout=10.0)
