"""End-to-end serving acceptance drill (tier-2).

The full path, as deployed: train → export → ``cli/serve.py`` server
SUBPROCESS on an ephemeral port → ``scripts/load_gen.py`` driving 256
concurrent requests through real HTTP → SLO rollup via
``scripts/analyze_trace.py`` → SIGTERM drain to a clean exit 0.

Logit parity is asserted BITWISE: a request's rows served inside a
coalesced padded batch must match the unbatched direct forward exactly
(same jitted computation, row-independent ops — verified, not assumed).
"""

import json
import os
import pathlib
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest
from test_train_lenet import lenet_config

from distributed_tensorflow_framework_tpu.core import telemetry
from distributed_tensorflow_framework_tpu.serve import (
    export_checkpoint,
    load_artifact,
)
from distributed_tensorflow_framework_tpu.train import Trainer

REPO = pathlib.Path(__file__).resolve().parent.parent

pytestmark = [pytest.mark.slow, pytest.mark.serve]


def _post(url, payload, timeout=60.0):
    body = json.dumps(payload).encode()
    req = urllib.request.Request(
        url + "/predict", data=body,
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.load(resp)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")
    except (urllib.error.URLError, OSError):
        return 0, {}


def _wait_for_endpoint(path, proc, timeout=180.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            raise AssertionError(
                f"server exited rc={proc.returncode} before serving:\n"
                f"{proc.stdout.read()}")
        if os.path.isfile(path):
            with open(path) as fh:
                return json.load(fh)
        time.sleep(0.5)
    raise AssertionError(f"no endpoint.json at {path} after {timeout}s")


def test_serving_acceptance_drill(devices, tmp_path):
    # 1. Train a short lenet run with a committed checkpoint.
    cfg = lenet_config(**{
        "checkpoint.directory": str(tmp_path / "ckpt"),
        "checkpoint.async_save": False,
        "checkpoint.save_interval_steps": 10,
        "train.total_steps": 10,
    })
    trainer = Trainer(cfg)
    trainer.build()
    trainer.train()

    # 2. Export onto the 1-device serving mesh (training mesh was the
    # full 8-device data mesh, so this is a real reshard).
    cfg.serve.data = 1
    cfg.serve.allow_reshard = True
    art_dir = export_checkpoint(cfg, str(tmp_path / "artifact"))
    artifact = load_artifact(art_dir)

    # 3. Stand the server up as a real subprocess on an ephemeral port.
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, "-m",
         "distributed_tensorflow_framework_tpu.cli.serve",
         "--artifact", art_dir,
         "--set", "serve.port=0",
         "--set", "serve.max_batch_size=8",
         "--set", "serve.max_wait_ms=5",
         "--set", "serve.report_interval_s=0.5"],
        cwd=str(REPO), env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)
    try:
        endpoint = _wait_for_endpoint(
            os.path.join(art_dir, "serve_logs", "endpoint.json"), proc)
        url = endpoint["url"]

        # 4. 256 requests through the load generator (closed 32-way
        # concurrent + open-loop), SERVE_BENCH.json written.
        bench_path = tmp_path / "SERVE_BENCH.json"
        gen = subprocess.run(
            [sys.executable, str(REPO / "scripts" / "load_gen.py"),
             "--endpoint", url, "--requests", "256", "--concurrency", "32",
             "--rate", "200", "--mode", "both", "--out", str(bench_path)],
            cwd=str(REPO), env=env, capture_output=True, text=True,
            timeout=600)
        assert gen.returncode == 0, gen.stdout + gen.stderr
        bench = json.loads(bench_path.read_text())
        assert bench["schema"] == "dtf-serve-bench/2"
        assert bench["fleet"] is None  # single server, no router section
        assert len(bench["runs"]) == 2
        for run in bench["runs"]:
            assert run["ok"] == 256, run
            assert run["latency_ms"]["p99"] >= run["latency_ms"]["p50"] > 0
            assert run["requests_per_sec"] > 0
        # The server actually coalesced: fewer batches than requests.
        assert 0 < bench["server_split"]["batches"] < 512
        assert bench["server_split"]["compute_ms"] > 0

        # 5. Parity: the same rows served inside a coalesced batch and
        # via the direct in-process forward must match BITWISE.
        rng = np.random.default_rng(0)
        images = rng.normal(size=(3, 28, 28, 1)).astype(np.float32)
        from distributed_tensorflow_framework_tpu.models import get_model

        model = get_model(artifact.model_config)
        direct = np.asarray(
            model.apply({"params": artifact.params}, images, train=False))
        payload = {"inputs": {"image": images.tolist()}}
        statuses, outputs = [], []
        lock = threading.Lock()

        def fire():
            s, out = _post(url, payload)
            with lock:
                statuses.append(s)
                outputs.append(out)

        threads = [threading.Thread(target=fire) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert statuses == [200] * 8, statuses
        for out in outputs:
            served = np.asarray(out["outputs"], np.float32)
            assert served.shape == direct.shape
            assert np.array_equal(served, direct), (
                f"batched logits diverge from direct forward by "
                f"{np.max(np.abs(served - direct))}")

        # 6. SLO rollup through the analyze_trace.py surface.
        events_path = endpoint["events"]
        rollup = subprocess.run(
            [sys.executable, str(REPO / "scripts" / "analyze_trace.py"),
             events_path],
            cwd=str(REPO), env=env, capture_output=True, text=True,
            timeout=120)
        assert rollup.returncode == 0, rollup.stdout + rollup.stderr
        assert "serving:" in rollup.stdout
        assert "p99" in rollup.stdout
        assert "req/s" in rollup.stdout

        # 7. SIGTERM drain: requests in flight when the signal lands
        # either complete (200) or are refused cleanly (503/closed) —
        # never a hung client — and the process exits 0.
        drain_statuses = []

        def fire_during_drain():
            s, _ = _post(url, payload, timeout=30.0)
            with lock:
                drain_statuses.append(s)

        drainers = [threading.Thread(target=fire_during_drain)
                    for _ in range(16)]
        for t in drainers:
            t.start()
        proc.send_signal(signal.SIGTERM)
        for t in drainers:
            t.join()
        assert proc.wait(timeout=120) == 0, proc.stdout.read()
        assert set(drain_statuses) <= {200, 503, 0}, drain_statuses
        # The drain left its telemetry record, and it drained clean.
        drained = [ev for ev in telemetry.read_events(events_path)
                   if (ev.get("health") or {}).get("event") == "serve_drain"]
        assert drained and drained[-1]["health"]["clean"] is True
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)
