"""Supervision: backoff, crash-loop breaker, heartbeat watchdog, rc=83.

Unit tests cover the pure decision logic in core/supervision.py; the
subprocess tests drive scripts/train_resilient.py with cheap stand-in
children (no JAX) to pin the supervisor's contract: graceful preemption
relaunches without consuming the attempt budget, a stalled heartbeat gets
the child killed within the staleness budget, and a deterministic crash
loop halts early with a structured report.
"""

import json
import os
import random
import subprocess
import sys
import time

import pytest

from distributed_tensorflow_framework_tpu.core import supervision, telemetry

SCRIPT = "scripts/train_resilient.py"


def run(args, timeout=120):
    return subprocess.run(
        [sys.executable, SCRIPT, *args], env=dict(os.environ),
        capture_output=True, text=True, timeout=timeout,
    )


# ---------------------------------------------------------------- units --
def test_backoff_doubles_and_caps():
    kw = {"base": 2.0, "cap": 9.0, "jitter": 0.0}
    assert [supervision.backoff_seconds(i, **kw) for i in (1, 2, 3, 4)] == \
        [2.0, 4.0, 8.0, 9.0]
    assert supervision.backoff_seconds(1, base=0.0) == 0.0


def test_backoff_jitter_bounds():
    rng = random.Random(7)
    for i in range(1, 6):
        d = supervision.backoff_seconds(i, base=1.0, cap=60.0, jitter=0.5,
                                        rng=rng)
        nominal = min(60.0, 2.0 ** (i - 1))
        assert 0.5 * nominal <= d <= 1.5 * nominal


def test_crash_loop_breaker():
    b = supervision.CrashLoopBreaker(threshold=2)
    assert not b.record(rc=1, last_step=10, ckpt_step=5)
    # progress (new ckpt step) resets the streak — transient
    assert not b.record(rc=1, last_step=10, ckpt_step=10)
    # identical signature twice in a row trips it
    assert not b.record(rc=1, last_step=12, ckpt_step=10)
    assert b.record(rc=1, last_step=12, ckpt_step=10)
    report = b.report()
    assert report["verdict"] == "deterministic_crash_loop"
    assert report["rc"] == 1 and report["streak"] == 2
    assert report["attempts_recorded"] == 4


def test_crash_loop_breaker_hung_is_transient():
    b = supervision.CrashLoopBreaker(threshold=2)
    for _ in range(5):  # watchdog kills never accumulate a streak
        assert not b.record(rc=137, last_step=None, ckpt_step=None, hung=True)
    # threshold=0 disables entirely
    b0 = supervision.CrashLoopBreaker(threshold=0)
    for _ in range(5):
        assert not b0.record(rc=1, last_step=None, ckpt_step=None)


def test_heartbeat_age_pid_scoped(tmp_path):
    path = str(tmp_path / "heartbeat.json")
    assert supervision.heartbeat_age_s(path) is None  # no file yet
    now = time.time()
    json.dump({"pid": 12345, "t": now - 30.0}, open(path, "w"))
    age = supervision.heartbeat_age_s(path, pid=12345, now=now)
    assert age == pytest.approx(30.0)
    # another child's record reads as "no heartbeat yet", not staleness
    assert supervision.heartbeat_age_s(path, pid=999, now=now) is None
    # record without a timestamp falls back to file mtime
    json.dump({"pid": 12345}, open(path, "w"))
    assert supervision.heartbeat_age_s(path, pid=12345) < 10.0


def test_graceful_rc_is_not_a_signal_code():
    assert supervision.GRACEFUL_PREEMPT_RC not in (130, 143)
    assert not 128 <= supervision.GRACEFUL_PREEMPT_RC <= 192


# ---------------------------------------------- supervisor loop (e2e) --
def test_preemption_relaunches_without_consuming_budget(tmp_path):
    """rc=83 (graceful preemption) relaunches immediately and does NOT
    count against --max-attempts: with a budget of ONE attempt, a child
    that preempts once and then succeeds still finishes."""
    marker = tmp_path / "preempted_once"
    prog = (
        "import pathlib, sys\n"
        "p = pathlib.Path(r'%s')\n"
        "if p.exists():\n"
        "    sys.exit(0)\n"
        "p.write_text('x')\n"
        "sys.exit(%d)\n" % (marker, supervision.GRACEFUL_PREEMPT_RC)
    )
    r = run(["--max-attempts", "1", "--events", "-", "--",
             sys.executable, "-c", prog])
    assert r.returncode == 0, r.stderr
    assert "graceful preemption" in r.stderr
    assert "done (attempt 1)" in r.stderr
    assert r.stderr.count("attempt 1/1") == 2  # relaunched, budget intact


def test_watchdog_kills_stalled_child(tmp_path):
    """A child that heartbeats once and then wedges must be SIGKILLed
    within the staleness budget — not waited on forever."""
    hb = tmp_path / "heartbeat.json"
    prog = (
        "import json, os, time\n"
        "json.dump({'pid': os.getpid(), 't': time.time(),"
        " 'last_completed_step': 7}, open(r'%s', 'w'))\n"
        "time.sleep(120)\n" % hb
    )
    t0 = time.monotonic()
    r = run(["--max-attempts", "1", "--heartbeat-file", str(hb),
             "--heartbeat-timeout", "1", "--heartbeat-poll", "0.3",
             "--events", "-", "--", sys.executable, "-c", prog])
    elapsed = time.monotonic() - t0
    assert r.returncode == 137, (r.returncode, r.stderr)  # 128 + SIGKILL
    assert "killing hung child" in r.stderr
    assert "(hung, last_step=7" in r.stderr
    assert elapsed < 60, f"watchdog took {elapsed:.0f}s"


def test_startup_grace_kills_silent_child(tmp_path):
    """--startup-grace bounds 'never heartbeated at all' (a child wedged
    before its first step)."""
    hb = tmp_path / "never_written.json"
    r = run(["--max-attempts", "1", "--heartbeat-file", str(hb),
             "--heartbeat-timeout", "30", "--heartbeat-poll", "0.3",
             "--startup-grace", "1", "--events", "-", "--",
             sys.executable, "-c", "import time; time.sleep(120)"])
    assert r.returncode == 137, (r.returncode, r.stderr)
    assert "startup grace" in r.stderr


def test_crash_loop_breaker_halts_early(tmp_path):
    """A deterministic crash (same rc, no progress, attempt after attempt)
    must stop at --crash-loop-threshold with a structured report, not burn
    the whole attempt budget."""
    events = tmp_path / "supervisor_events.jsonl"
    r = run(["--max-attempts", "10", "--retry-sleep", "0.05", "--jitter",
             "0", "--crash-loop-threshold", "2", "--events", str(events),
             "--", sys.executable, "-c", "import sys; sys.exit(5)"])
    assert r.returncode == 5
    assert "CRASH LOOP" in r.stderr
    assert "deterministic_crash_loop" in r.stderr
    assert "attempt 2 exited rc=5" in r.stderr
    assert "attempt 3/10" not in r.stderr  # halted early

    evs = list(telemetry.read_events(str(events), strict=True))
    kinds = [e["kind"] for e in evs]
    assert kinds.count(telemetry.KIND_SUPERVISOR_ATTEMPT) == 2
    assert telemetry.KIND_CRASH_LOOP in kinds
    loop_ev = next(e for e in evs if e["kind"] == telemetry.KIND_CRASH_LOOP)
    assert loop_ev["extra"]["verdict"] == "deterministic_crash_loop"
    summary = telemetry.summarize_events(str(events))
    assert summary["recovery"]["supervisor_attempts"] == {"crashed": 2}
    assert summary["recovery"]["crash_loop"]["verdict"] == \
        "deterministic_crash_loop"


# -------------------------------------- anomaly escalation (rc=85) ----


def test_anomaly_rc_is_distinct():
    rc = supervision.ANOMALY_ESCALATION_RC
    assert rc != 0
    assert rc != supervision.GRACEFUL_PREEMPT_RC
    assert rc not in (130, 143)
    assert not 128 <= rc <= 192  # never collides with 128+signal codes


def test_crash_loop_breaker_transient_never_accumulates():
    """transient=True (the rc=85 persistent-anomaly path) must never feed
    the streak: the child already classified the failure, and an identical
    signature N times over is expected while the run chews through a
    poisoned data region."""
    b = supervision.CrashLoopBreaker(threshold=2)
    for _ in range(5):
        assert not b.record(rc=85, last_step=30, ckpt_step=20,
                            transient=True)
    # a real crash right after still gets its full threshold
    assert not b.record(rc=1, last_step=30, ckpt_step=20)
    assert b.record(rc=1, last_step=30, ckpt_step=20)


def test_persistent_anomaly_classified_without_burning_breaker(tmp_path):
    """A child exiting ANOMALY_ESCALATION_RC repeatedly — more times than
    --crash-loop-threshold — must be classified persistent_anomaly,
    relaunched with backoff, and NEVER tripped as a crash loop; once the
    child recovers, the supervisor exits 0."""
    events = tmp_path / "supervisor_events.jsonl"
    marker = str(tmp_path / "attempts.txt")
    prog = (
        "import os, sys\n"
        "m = sys.argv[1]\n"
        "n = int(open(m).read()) if os.path.exists(m) else 0\n"
        "open(m, 'w').write(str(n + 1))\n"
        f"sys.exit({supervision.ANOMALY_ESCALATION_RC} if n < 3 else 0)\n"
    )
    r = run(["--max-attempts", "10", "--retry-sleep", "0.05", "--jitter",
             "0", "--crash-loop-threshold", "2", "--events", str(events),
             "--", sys.executable, "-c", prog, marker])
    assert r.returncode == 0, r.stderr[-3000:]
    assert "persistent_anomaly" in r.stderr
    assert "CRASH LOOP" not in r.stderr  # 3 identical rc=85 > threshold=2
    assert "done (attempt 4)" in r.stderr

    evs = list(telemetry.read_events(str(events), strict=True))
    assert telemetry.KIND_CRASH_LOOP not in [e["kind"] for e in evs]
    summary = telemetry.summarize_events(str(events))
    assert summary["recovery"]["supervisor_attempts"] == {
        "persistent_anomaly": 3, "done": 1}
    assert summary["recovery"]["crash_loop"] is None
