"""Telemetry subsystem (core/telemetry.py + collectives tally + run-health
hooks): the ONE event schema every emitter shares (docs/OBSERVABILITY.md).

Covers the schema contract (round-trip, version check, reserved-field
policy), the per-collective byte counters under a real 2-device shard_map
trace, and the run-health hooks (heartbeat, MoE-collapse detector, NaN
provenance) on synthetic inputs.
"""

import json
import math
import os
from types import SimpleNamespace

import jax
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from distributed_tensorflow_framework_tpu.core import telemetry
from distributed_tensorflow_framework_tpu.core.metrics import MetricWriter
from distributed_tensorflow_framework_tpu.parallel import collectives as coll
from distributed_tensorflow_framework_tpu.train import hooks as hooks_lib


# ------------------------------------------------------------- schema ----


def test_event_roundtrip(tmp_path):
    path = str(tmp_path / "events.jsonl")
    w = telemetry.TelemetryWriter(path, run_id="test-run")
    w.emit_run_meta(argv=["prog", "--x"], model="lenet5")
    w.emit(
        telemetry.KIND_TRAIN_STEP,
        step=3,
        metrics={"loss": 1.5},
        phases={"infeed": 0.4},
        throughput={"examples_per_sec": 100.0},
        collectives={"pmean_calls": 1, "pmean_bytes": 8, "total_bytes": 8},
    )
    w.close()

    evs = list(telemetry.read_events(path))
    assert [e["kind"] for e in evs] == [
        telemetry.KIND_RUN_META, telemetry.KIND_TRAIN_STEP]
    for e in evs:
        assert e["schema"] == telemetry.SCHEMA
        assert e["run_id"] == "test-run"
        assert telemetry.validate_event(e) == []
    meta, step_ev = evs
    assert meta["extra"]["argv"] == "prog --x"
    assert step_ev["step"] == 3
    assert step_ev["metrics"] == {"loss": 1.5}
    assert step_ev["phases"] == {"infeed": 0.4}
    assert step_ev["collectives"]["total_bytes"] == 8


def test_schema_version_is_enforced(tmp_path):
    path = str(tmp_path / "events.jsonl")
    w = telemetry.TelemetryWriter(path, run_id="r")
    w.emit(telemetry.KIND_TRAIN_STEP, step=1, metrics={"loss": 1.0})
    w.close()
    with open(path, "a") as fh:
        bad = {"schema": "dtf-telemetry/999", "run_id": "r",
               "kind": "train_step", "t": 0.0}
        fh.write(json.dumps(bad) + "\n")

    with pytest.raises(ValueError, match="schema"):
        list(telemetry.read_events(path))
    # Non-strict readers skip the unknown version instead of dying.
    lenient = list(telemetry.read_events(path, strict=False))
    assert len(lenient) == 1 and lenient[0]["step"] == 1


def test_validate_event_rejects_unknown_top_level_fields():
    ev = telemetry.make_event(
        telemetry.KIND_BENCH, run_id="r", metrics={"value": 1.0})
    assert telemetry.validate_event(ev) == []
    ev["mfu"] = 0.5  # belongs under roofline/extra, not top-level
    errors = telemetry.validate_event(ev)
    assert errors and "mfu" in errors[0]


def test_split_metrics_routes_phases_and_throughput():
    metrics, phases, throughput = telemetry.split_metrics({
        "loss": 2.0,
        "time_infeed_ms": 1.25,
        "time_dispatch_ms": 0.5,
        "examples_per_sec": 10.0,
        "tokens_per_sec": 640.0,
    })
    assert metrics == {"loss": 2.0}
    assert phases == {"infeed": 1.25, "dispatch": 0.5}
    assert throughput == {"examples_per_sec": 10.0, "tokens_per_sec": 640.0}


def test_metric_writer_emits_schema_events(tmp_path):
    writer = MetricWriter(logdir=str(tmp_path))
    writer.write(5, {"loss": 0.5, "time_infeed_ms": 1.0,
                     "examples_per_sec": 42.0},
                 collectives={"total_bytes": 128})
    writer.close()
    evs = list(telemetry.read_events(os.path.join(str(tmp_path),
                                                  "events.jsonl")))
    assert len(evs) == 1
    ev = evs[0]
    assert telemetry.validate_event(ev) == []
    assert ev["step"] == 5
    assert ev["metrics"] == {"loss": 0.5}
    assert ev["phases"] == {"infeed": 1.0}
    assert ev["throughput"] == {"examples_per_sec": 42.0}
    assert ev["collectives"] == {"total_bytes": 128}


# --------------------------------------------- collective byte counters ----


def test_collective_tally_2dev_shard_map(devices):
    mesh = Mesh(np.array(jax.devices()[:2]), ("data",))
    x = jax.device_put(
        np.arange(8, dtype=np.float32),
        jax.sharding.NamedSharding(mesh, P("data")))

    def f(x):
        y = coll.pmean(x, "data")            # local shard: 4 f32 = 16 B
        z = coll.all_gather(x, "data")       # local shard: 4 f32 = 16 B
        return y, z

    mapped = jax.jit(coll.shard_map(
        f, mesh=mesh, in_specs=P("data"), out_specs=(P(None), P(None)),
        check_vma=False))
    with coll.tally() as t:
        out = mapped(x)
    jax.block_until_ready(out)

    s = t.summary()
    # Ring convention (CollectiveTally docstring): all-reduce counts 2x
    # its 16 B payload, all-gather counts its OUTPUT (n x the shard).
    assert s["pmean_calls"] == 1 and s["pmean_bytes"] == 32
    assert s["all_gather_calls"] == 1 and s["all_gather_bytes"] == 32
    assert s["total_bytes"] == 64
    # f32 wire == logical dtype: no compression, totals coincide.
    assert s["total_logical_bytes"] == 64

    # Counters record at TRACE time: a second dispatch of the same
    # executable adds nothing (the numbers describe every step).
    with coll.tally() as t2:
        jax.block_until_ready(mapped(x))
    assert t2.summary() == {"total_bytes": 0, "total_logical_bytes": 0}


def test_collective_tally_allreduce_gradients(devices):
    mesh = Mesh(np.array(jax.devices()[:2]), ("data",))
    grads = {"a": np.ones((4, 2), np.float32), "b": np.ones((6,), np.float32)}
    sharding = jax.sharding.NamedSharding(mesh, P())
    grads = jax.device_put(grads, sharding)

    mapped = jax.jit(coll.shard_map(
        lambda g: coll.allreduce_gradients(g, ("data",)),
        mesh=mesh, in_specs=P(), out_specs=P(), check_vma=False))
    with coll.tally() as t:
        jax.block_until_ready(mapped(grads))
    s = t.summary()
    assert s["allreduce_grads_pmean_calls"] == 2  # one per tree leaf
    assert s["allreduce_grads_pmean_bytes"] == (8 + 6) * 4 * 2  # ring 2x
    assert s["total_bytes"] == (8 + 6) * 4 * 2
    assert s["total_logical_bytes"] == s["total_bytes"]


def test_collective_tally_int8_wire_vs_logical(devices):
    """The int8 block-scaled all-reduce must tally wire bytes (int8 codes
    + f32 scales) SEPARATELY from logical bytes — their ratio is the
    compression the A/B exists to measure."""
    mesh = Mesh(np.array(jax.devices()[:2]), ("data",))
    grads = {"w": np.ones((256,), np.float32)}
    grads = jax.device_put(grads, jax.sharding.NamedSharding(mesh, P()))

    mapped = jax.jit(coll.shard_map(
        lambda g: coll.allreduce_gradients(
            g, ("data",), compute_dtype="int8", block_size=64),
        mesh=mesh, in_specs=P(), out_specs=P(), check_vma=False))
    with coll.tally() as t:
        out = jax.block_until_ready(mapped(grads))
    s = t.summary()

    # scatter phase: 256 int8 codes + 4 blocks x 4 B scales = 272 wire,
    # vs 256 f32 = 1024 logical. gather phase: 128-elem chunk x n=2
    # output + 2x2 scales = 272 wire vs 1024 logical.
    assert s["allreduce_grads_q8_scatter_bytes"] == 272
    assert s["allreduce_grads_q8_scatter_logical_bytes"] == 1024
    assert s["allreduce_grads_q8_gather_bytes"] == 272
    assert s["allreduce_grads_q8_gather_logical_bytes"] == 1024
    assert s["total_bytes"] == 544
    assert s["total_logical_bytes"] == 2048
    # A constant tree quantizes exactly: the mean of all-ones is all-ones.
    np.testing.assert_array_equal(np.asarray(out["w"]), np.ones(256))


def test_summarize_collectives_rollup(tmp_path):
    """Per-step tallies ride train_step events; the run summary reports
    the LAST one (static per compiled program) with the wire-compression
    ratio."""
    path = str(tmp_path / "events.jsonl")
    w = telemetry.TelemetryWriter(path, run_id="coll")
    w.emit(telemetry.KIND_TRAIN_STEP, step=1, metrics={"loss": 1.0},
           collectives={"total_bytes": 544, "total_logical_bytes": 2048})
    w.close()
    s = telemetry.summarize_events(path)
    assert s["collectives"] == {"total_bytes": 544,
                                "total_logical_bytes": 2048,
                                "wire_compression": round(2048 / 544, 3)}
    text = telemetry.format_run_summary(s)
    assert "collectives: 544 wire bytes/step (2,048 logical" in text
    assert "x compression" in text


def test_summarize_without_collectives(tmp_path):
    path = str(tmp_path / "events.jsonl")
    w = telemetry.TelemetryWriter(path, run_id="nocoll")
    w.emit(telemetry.KIND_TRAIN_STEP, step=1, metrics={"loss": 1.0})
    w.close()
    s = telemetry.summarize_events(path)
    assert s["collectives"] is None
    assert "collectives:" not in telemetry.format_run_summary(s)


# ------------------------------------------------------- run-health hooks ----


def _trainer_stub(tmp_path, **over):
    """The minimal Trainer surface the hooks touch."""
    events = str(tmp_path / "events.jsonl")
    writer = SimpleNamespace(
        telemetry=telemetry.TelemetryWriter(events, run_id="hook-run"))
    stub = SimpleNamespace(
        run_id="hook-run",
        host_step=0,
        writer=writer,
        config=SimpleNamespace(checkpoint=SimpleNamespace(
            directory=str(tmp_path / "ckpt"))),
        _events_path=events,
    )
    for k, v in over.items():
        setattr(stub, k, v)
    return stub


def test_heartbeat_hook_writes_atomic_liveness_file(tmp_path):
    hb_path = str(tmp_path / "heartbeat.json")
    hook = hooks_lib.HeartbeatHook(hb_path, min_interval_s=0.0)
    trainer = _trainer_stub(tmp_path)

    hook.on_start(trainer)
    rec = json.load(open(hb_path))
    assert rec["status"] == "running" and rec["step"] == 0
    assert rec["schema"] == telemetry.SCHEMA
    assert rec["run_id"] == "hook-run"

    hook.after_step(trainer, 3, {"loss": 1.25})
    trainer.host_step = 3
    hook.on_end(trainer)
    rec = json.load(open(hb_path))
    assert rec["status"] == "finished" and rec["step"] == 3
    assert rec["last_metrics"] == {"loss": 1.25}
    assert rec["pid"] == os.getpid()
    assert not os.path.exists(hb_path + ".tmp")


def test_heartbeat_hook_respects_min_interval(tmp_path):
    hb_path = str(tmp_path / "hb.json")
    hook = hooks_lib.HeartbeatHook(hb_path, min_interval_s=3600.0)
    trainer = _trainer_stub(tmp_path)
    hook.on_start(trainer)
    t0 = json.load(open(hb_path))["t"]
    hook.after_step(trainer, 1, {"loss": 1.0})  # within interval: no write
    assert json.load(open(hb_path))["t"] == t0


def test_moe_collapse_hook_fires_on_induced_collapse(tmp_path):
    hook = hooks_lib.MoECollapseHook(patience=2)
    trainer = _trainer_stub(tmp_path)

    # Healthy routing: balanced aux loss, no drops — never fires.
    for step in (1, 2, 3):
        hook.after_step(trainer, step, {"moe_drop_frac": 0.01,
                                        "moe_aux_loss": 1.02})
    assert hook.fired_steps == []

    # Induced collapse fixture: most tokens racing one expert.
    hook.after_step(trainer, 4, {"moe_drop_frac": 0.7, "moe_aux_loss": 5.0})
    assert hook.fired_steps == []  # patience not yet met
    hook.after_step(trainer, 5, {"moe_drop_frac": 0.72, "moe_aux_loss": 5.5})
    assert hook.fired_steps == [5]

    trainer.writer.telemetry.close()
    evs = list(telemetry.read_events(trainer._events_path,
                                     kind=telemetry.KIND_HEALTH))
    assert len(evs) == 1
    h = evs[0]["health"]
    assert h["warning"] == "moe_collapse" and h["streak"] == 2
    assert h["moe_drop_frac_value"] == pytest.approx(0.72)


def test_moe_collapse_streak_resets_on_recovery(tmp_path):
    hook = hooks_lib.MoECollapseHook(patience=2)
    trainer = _trainer_stub(tmp_path)
    hook.after_step(trainer, 1, {"moe_drop_frac": 0.9})
    hook.after_step(trainer, 2, {"moe_drop_frac": 0.0})  # transient recovered
    hook.after_step(trainer, 3, {"moe_drop_frac": 0.9})
    assert hook.fired_steps == []


def test_nan_guard_provenance(tmp_path):
    trainer = _trainer_stub(
        tmp_path,
        _ckpt_manager=SimpleNamespace(latest_step=lambda: 7),
    )
    hook = hooks_lib.NaNGuardHook()
    with pytest.raises(FloatingPointError) as exc:
        hook.after_step(trainer, 9, {"loss": float("nan")})
    msg = str(exc.value)
    expected_ckpt = os.path.join(trainer.config.checkpoint.directory, "7")
    assert "loss" in msg and "step 9" in msg and expected_ckpt in msg

    trainer.writer.telemetry.close()
    evs = list(telemetry.read_events(trainer._events_path,
                                     kind=telemetry.KIND_FAILURE))
    assert len(evs) == 1
    h = evs[0]["health"]
    assert h["failure"] == "non_finite_metric"
    assert h["metric"] == "loss"
    assert math.isnan(float(h["value"]))
    assert h["last_good_checkpoint"] == expected_ckpt
    assert evs[0]["step"] == 9


# ------------------------------------------- recovery-ladder rollups ----


def test_summarize_counts_recovery_ladder_events(tmp_path):
    """analyze_trace run summaries must account for every ladder rung:
    anomalies, rollbacks, skipped batches, and infeed stall retries."""
    path = str(tmp_path / "events.jsonl")
    w = telemetry.TelemetryWriter(path, run_id="ladder")
    w.emit(telemetry.KIND_ANOMALY, step=30,
           health={"anomaly": "non_finite_metric", "metric": "grad_norm",
                   "value": "nan"})
    w.emit(telemetry.KIND_ROLLBACK, step=30,
           health={"from_step": 30, "to_step": 20,
                   "consecutive_rollbacks": 1})
    w.emit(telemetry.KIND_BATCH_SKIPPED, step=30,
           health={"from_step": 21, "to_step": 30, "batches": 10})
    for attempt in (1, 2, 3):
        w.emit(telemetry.KIND_INFEED_STALL, step=12,
               health={"deadline_s": 0.5, "attempt": attempt,
                       "max_retries": 20})
    w.close()

    s = telemetry.summarize_events(path)
    rec = s["recovery"]
    assert rec["anomalies"] == [{"step": 30, "anomaly": "non_finite_metric",
                                 "metric": "grad_norm"}]
    assert rec["rollbacks"] == [{"from_step": 30, "to_step": 20}]
    assert rec["batches_skipped"] == 10
    assert rec["infeed_stalls"] == 3

    text = telemetry.format_run_summary(s)
    assert "anomaly at step 30: non_finite_metric (grad_norm)" in text
    assert "rollback: step 30 -> 20" in text
    assert "batches skipped: 10" in text
    assert "infeed stalls retried: 3" in text


def test_summarize_without_ladder_events_reports_none(tmp_path):
    path = str(tmp_path / "events.jsonl")
    w = telemetry.TelemetryWriter(path, run_id="quiet")
    w.emit(telemetry.KIND_TRAIN_STEP, step=1, metrics={"loss": 1.0})
    w.close()
    s = telemetry.summarize_events(path)
    assert s["recovery"]["anomalies"] == []
    assert s["recovery"]["batches_skipped"] == 0
    assert "recovery activity: none" in telemetry.format_run_summary(s)


def test_summarize_pipeline_schedule_rollup(tmp_path):
    """A pipeline_schedule event plus train_step events roll up into the
    pipeline section: schedule identity, analytic bubble, the per-step
    logged bubble, and steady-state throughput (median of the back half
    of logged rates, past the compile ramp)."""
    path = str(tmp_path / "events.jsonl")
    w = telemetry.TelemetryWriter(path, run_id="pp")
    w.emit(telemetry.KIND_PIPELINE, schedule="1f1b", stages=4,
           microbatches=8, virtual_stages=1,
           bubble_frac=3 / 11, peak_inflight=7.0)
    rates = [2.0, 9.0, 13.0, 14.0, 13.9, 14.1]  # slow compile-step head
    for i, r in enumerate(rates):
        w.emit(telemetry.KIND_TRAIN_STEP, step=i * 10,
               metrics={"loss": 5.0, "pipe_bubble_frac": 3 / 11},
               throughput={"examples_per_sec": r})
    w.close()

    pipe = telemetry.summarize_events(path)["pipeline"]
    assert pipe["schedule"] == "1f1b"
    assert pipe["stages"] == 4
    assert pipe["bubble_frac"] == pytest.approx(3 / 11)
    assert pipe["bubble_frac_logged"] == pytest.approx(3 / 11)
    assert pipe["steady_examples_per_sec"] == pytest.approx(14.0)

    text = telemetry.format_run_summary(
        telemetry.summarize_events(path))
    assert "pipeline: 1f1b S=4 M=8" in text
    assert "bubble 0.2727" in text
    assert "residency 7 acts" in text
    assert "steady 14.0 ex/s" in text


def test_summarize_without_pipeline_events(tmp_path):
    path = str(tmp_path / "events.jsonl")
    w = telemetry.TelemetryWriter(path, run_id="nopipe")
    w.emit(telemetry.KIND_TRAIN_STEP, step=1, metrics={"loss": 1.0})
    w.close()
    s = telemetry.summarize_events(path)
    assert s["pipeline"] is None
    assert "pipeline:" not in telemetry.format_run_summary(s)


def test_summarize_mesh_resize_and_reshard_rollup(tmp_path):
    """The elastic events (ISSUE 6) join the recovery section: a
    supervisor mesh_resized and a restore-side ckpt_resharded both count
    as recovery activity and render with their axis transitions."""
    path = str(tmp_path / "events.jsonl")
    w = telemetry.TelemetryWriter(path, run_id="elastic")
    w.emit(telemetry.KIND_MESH_RESIZED,
           from_axes={"data": 8}, to_axes={"data": 4}, visible_devices=4,
           global_batch=32, grad_accum=2, effective_batch_preserved=True)
    w.emit(telemetry.KIND_CKPT_RESHARDED, step=20,
           from_axes={"data": 8}, to_axes={"data": 4}, leaf_count=12,
           respec_agreement="12/8")
    w.close()
    s = telemetry.summarize_events(path)
    rec = s["recovery"]
    assert rec["mesh_resizes"] == [{"from_axes": {"data": 8},
                                    "to_axes": {"data": 4},
                                    "visible_devices": 4}]
    assert rec["ckpt_reshards"] == [{"step": 20, "from_axes": {"data": 8},
                                     "to_axes": {"data": 4},
                                     "leaf_count": 12}]
    text = telemetry.format_run_summary(s)
    assert "mesh resized: {data:8} -> {data:4} (4 devices visible)" in text
    assert "checkpoint resharded at step 20: {data:8} -> {data:4}" in text


def test_summarize_rolls_up_every_kind(tmp_path):
    """One event of EVERY telemetry kind → the summary accounts for each
    (the marker-audit's rollup guarantee, exercised end-to-end). New
    kinds must be added here — test_marker_audit.py enforces that every
    KIND_* has both a rollup and a test reference."""
    path = str(tmp_path / "events.jsonl")
    w = telemetry.TelemetryWriter(path, run_id="all-kinds")
    w.emit_run_meta(argv=["train.py"], config_name="lenet",
                    mesh={"data": 8})  # KIND_RUN_META
    w.emit(telemetry.KIND_TRAIN_STEP, step=1, metrics={"loss": 1.0},
           throughput={"examples_per_sec": 10.0})
    w.emit(telemetry.KIND_EVAL, step=2, metrics={"eval_loss": 1.0})
    w.emit(telemetry.KIND_BENCH, metrics={"value": 1.0},
           workload="resnet50")
    w.emit(telemetry.KIND_BENCH_PROBE, platform="cpu")
    w.emit(telemetry.KIND_TRACE_SUMMARY, trace_dir="/tmp/t")
    w.emit(telemetry.KIND_HEALTH, step=3,
           health={"event": "moe_collapse"})
    w.emit(telemetry.KIND_FAILURE, step=3, health={"failure": "nan_loss"})
    w.emit(telemetry.KIND_CKPT_SAVE, step=4,
           metrics={"ckpt_save_blocked_ms": 1.0, "ckpt_save_total_ms": 2.0},
           async_save=True)
    w.emit(telemetry.KIND_STARTUP, step=4,
           time_to_first_step_s=2.5, restored_step=4)
    w.emit(telemetry.KIND_PIPELINE, schedule="gpipe", stages=2,
           microbatches=4, bubble_frac=0.2)
    w.emit(telemetry.KIND_ZERO_UPDATE, shards=8, buckets=3, bucket_mb=4.0,
           wire="float32", rs_wire_bytes=1024, ag_wire_bytes=1024,
           overlap_frac_est=0.6667, hidden_ms_est=0.01)
    w.emit(telemetry.KIND_ANOMALY, step=5,
           health={"anomaly": "loss_spike", "metric": "loss"})
    w.emit(telemetry.KIND_ROLLBACK, step=5,
           health={"from_step": 5, "to_step": 4})
    w.emit(telemetry.KIND_BATCH_SKIPPED, step=5, health={"batches": 2})
    w.emit(telemetry.KIND_INFEED_STALL, step=5, health={"attempt": 1})
    w.emit(telemetry.KIND_CKPT_QUARANTINED, step=4,
           health={"reason": "hash mismatch"})
    w.emit(telemetry.KIND_RESTORE_FALLBACK,
           health={"from_step": 4, "to_step": 2})
    w.emit(telemetry.KIND_SUPERVISOR_ATTEMPT, attempt=1, rc=137,
           classification="crashed")
    w.emit(telemetry.KIND_CRASH_LOOP, verdict="deterministic_crash_loop")
    w.emit(telemetry.KIND_MESH_RESIZED, from_axes={"data": 8},
           to_axes={"data": 4}, visible_devices=4)
    w.emit(telemetry.KIND_CKPT_RESHARDED, step=4, from_axes={"data": 8},
           to_axes={"data": 4}, leaf_count=8)
    w.emit(telemetry.KIND_SERVE_REQUEST,
           metrics={"rows": 2, "queue_wait_ms": 1.0, "latency_ms": 4.0})
    w.emit(telemetry.KIND_SERVE_BATCH,
           metrics={"rows": 2, "padded_rows": 4, "compute_ms": 3.0,
                    "queue_depth": 1})
    w.emit(telemetry.KIND_SERVE_QUEUE, metrics={"queue_depth": 2})
    w.emit(telemetry.KIND_SERVE_LATENCY,
           metrics={"p50_ms": 3.0, "p90_ms": 4.0, "p99_ms": 4.0, "count": 1},
           throughput={"requests_per_sec": 10.0, "rows_per_sec": 20.0})
    w.emit(telemetry.KIND_SERVE_RECOMPILE, bucket="rows2",
           metrics={"compile_ms": 50.0})
    w.emit(telemetry.KIND_DECODE_STEP,
           metrics={"rows": 3, "padded_rows": 4, "step_ms": 6.0,
                    "per_token_ms": 2.0, "occupancy": 0.75})
    w.emit(telemetry.KIND_KV_CACHE,
           metrics={"pages_used": 5, "pages_free": 3, "streams_active": 2,
                    "streams_waiting": 1, "evictions": 1},
           event="periodic")
    w.emit(telemetry.KIND_SERVE_ROUTE,
           metrics={"latency_ms": 5.0, "retries": 1, "status": 200},
           replica="r0", shed=False, deadline_exceeded=False)
    w.emit(telemetry.KIND_SERVE_EJECT, replica="r1", action="eject",
           reason="stale healthz")
    w.emit(telemetry.KIND_SERVE_RELOAD, metrics={"reload_ms": 120.0},
           replica="r0", ok=True, from_digest="aaaa", to_digest="bbbb")
    w.emit(telemetry.KIND_SCALE, metrics={"pressure": 0.91},
           action="up", reason="pressure 0.91 >= 0.75", replica="r3",
           from_replicas=3, to_replicas=4)
    w.emit(telemetry.KIND_ADMISSION, tenant="batch:nightly", priority=2,
           verdict="shed", retry_after_s=1.0)
    w.emit(telemetry.KIND_SPAN, metrics={"dur_ms": 12.5},
           trace="t" * 16, span="s" * 16, parent=None,
           name="serve.request", service="replica0", status="ok",
           t_start=1000.0, offset_s=0.0, attrs=None)
    w.emit(telemetry.KIND_GOODPUT, step=5,
           metrics={"wall_s": 10.0, "goodput_frac": 0.8},
           buckets={"step_compute": 8.0, "other": 2.0},
           counters={"ckpt_saves": 1}, t0=1000.0, final=True)
    w.emit(telemetry.KIND_MEMORY, step=5,
           metrics={"bytes_in_use": 100, "peak_bytes_in_use": 200,
                    "device_count": 8},
           source="train", source_kind="device_memory_stats",
           analysis={"argument_bytes": 50, "temp_bytes": 25,
                     "output_bytes": 25, "peak_bytes_est": 100})
    w.emit(telemetry.KIND_DATA_SHARD, step=0,
           shard={"process_index": 0, "process_count": 2, "host_batch": 8,
                  "global_batch": 16, "shard_mode": "block",
                  "data_parallel": 2})
    w.emit(telemetry.KIND_DATA_PACKING, step=5,
           metrics={"real_tokens": 90, "padded_tokens": 10,
                    "total_tokens": 100, "packing_efficiency": 0.9})
    w.emit(telemetry.KIND_DATA_STATE, step=4,
           plan={"action": "repartition", "from_processes": 4,
                 "to_processes": 2, "watermark": 2})
    w.emit(telemetry.KIND_AUTOTUNE_TRIAL, trial="sha256:abcd", status="done",
           score=2418.0, unit="images/sec/chip")
    w.close()

    s = telemetry.summarize_events(path)
    kind_values = {
        getattr(telemetry, name)
        for name in dir(telemetry) if name.startswith("KIND_")
    }
    assert kind_values <= set(s["kinds"]), (
        f"kinds never emitted by this test: {kind_values - set(s['kinds'])}"
    )
    assert s["meta"]["config_name"] == "lenet"
    assert s["evals"] == {"count": 1, "last_step": 2}
    assert s["bench"] == {"count": 1, "workloads": ["resnet50"]}
    assert s["bench_probes"] == 1
    assert s["trace_summaries"] == 1
    assert s["health_events"] == {"moe_collapse": 1}
    assert s["serve"]["requests"] == 1 and s["serve"]["batches"] == 1
    assert s["serve"]["queue_depth_max"] == 2
    assert s["fleet"]["requests"] == 1 and s["fleet"]["retries"] == 1
    assert s["fleet"]["ejects"] == [{"replica": "r1",
                                     "reason": "stale healthz"}]
    assert s["fleet"]["reloads"][0]["to_digest"] == "bbbb"
    assert s["fleet"]["scaling"]["ups"] == 1
    assert s["fleet"]["scaling"]["events"][0]["to_replicas"] == 4
    assert s["fleet"]["tenants"]["batch:nightly"]["shed"] == 1
    assert s["decode"]["tokens"] == 3 and s["decode"]["steps"] == 1
    assert s["decode"]["pages_used_max"] == 5
    assert s["decode"]["evictions"] == 1
    assert s["decode"]["streams_waiting_max"] == 1
    assert s["zero"]["shards"] == 8 and s["zero"]["buckets"] == 3
    assert s["goodput"]["attempts"] == 1
    assert s["goodput"]["goodput_frac"] == pytest.approx(0.8)
    assert s["memory"]["samples"] == 1
    assert s["memory"]["peak_bytes_in_use"] == 200
    assert s["spans"]["count"] == 1 and s["spans"]["traces"] == 1
    assert s["spans"]["services"] == {"replica0": 1}
    assert s["data"]["shard"]["shard_mode"] == "block"
    assert s["data"]["packing"]["packing_efficiency"] == 0.9
    assert s["recovery"]["data_restores"][0]["action"] == "repartition"
    assert s["autotune"]["ran"] == 1
    assert s["autotune"]["best"]["trial"] == "sha256:abcd"
    text = telemetry.format_run_summary(s)
    assert "run: config_name=lenet" in text
    assert "evals: 1 (last at step 2)" in text
    assert "bench results: 1 (resnet50)" in text
    assert "backend probes: 1" in text
    assert "trace summaries: 1" in text
    assert "health events: moe_collapse=1" in text
    assert "serving: 1 requests (2 rows) in 1 batches" in text
    assert "decode: 3 tokens in 1 steps" in text
    assert "kv cache: peak 5 pages in use" in text
    assert "bucket recompiles: 1 (rows2)" in text
    assert "fleet: 1 proxied" in text and "ejections: 1" in text
    assert "scaling: 1 up / 0 down (up->4@0.91)" in text
    assert "tenant batch:nightly: routed 0, shed 1" in text
    assert "zero update sharding: 8 shards, 3 buckets" in text
    assert "goodput: 80.0% of 10.0 s wall over 1 attempt(s)" in text
    assert "spans: 1 across 1 trace(s) [replica0=1]" in text
    assert "memory: 1 sample(s)" in text
    assert "data shard: host 0/2 reads 8 of 16 rows/batch (block mode)" \
        in text
    assert "packing: 90 real / 10 padded tokens, efficiency 0.900" in text
    assert "data state restored at step 4: repartition across 4 -> 2 " \
        "hosts (watermark 2)" in text
