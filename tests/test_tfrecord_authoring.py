"""Round-trip: scripts/make_imagenet_tfrecords.py → data/imagenet.py.

Authors shards from a directory-of-JPEGs tree and feeds them through the
real TFRecord pipeline, proving the authoring tool emits exactly the
schema the reader consumes (keys, 1-based labels, JPEG payload).
"""

import os
import subprocess
import sys

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

from distributed_tensorflow_framework_tpu.core.config import DataConfig  # noqa: E402
from distributed_tensorflow_framework_tpu.data.imagenet import make_imagenet  # noqa: E402

SCRIPT = os.path.join(os.path.dirname(__file__), "..", "scripts",
                      "make_imagenet_tfrecords.py")


@pytest.fixture(scope="module")
def authored(tmp_path_factory):
    src = tmp_path_factory.mktemp("raw")
    out = tmp_path_factory.mktemp("records")
    rng = np.random.default_rng(0)
    for split, per_class in (("train", 4), ("validation", 2)):
        for cls in ("n01", "n02", "n03"):
            cdir = src / split / cls
            cdir.mkdir(parents=True)
            for i in range(per_class):
                img = rng.integers(0, 255, (40, 32, 3), dtype=np.uint8)
                tf.io.write_file(str(cdir / f"img{i}.jpg"),
                                 tf.io.encode_jpeg(img))
        # One PNG to exercise the transcode branch.
        png = rng.integers(0, 255, (40, 32, 3), dtype=np.uint8)
        tf.io.write_file(str(src / split / "n01" / "extra.png"),
                         tf.io.encode_png(png))
        r = subprocess.run(
            [sys.executable, SCRIPT, str(src), str(out),
             "--split", split, "--shards", "2"],
            capture_output=True, text=True)
        assert r.returncode == 0, r.stderr
    return str(out)


def test_shards_and_label_map(authored):
    names = sorted(os.listdir(authored))
    assert "train-00000-of-00002" in names and "train-00001-of-00002" in names
    assert "validation-00000-of-00002" in names
    with open(os.path.join(authored, "labels.txt")) as fh:
        lines = [l.split() for l in fh.read().splitlines()]
    assert lines == [["1", "n01"], ["2", "n02"], ["3", "n03"]]


def test_pipeline_reads_authored_records(authored):
    cfg = DataConfig(name="imagenet", data_dir=authored, global_batch_size=4,
                     image_size=32, shuffle_buffer=8, seed=3)
    ds = make_imagenet(cfg, 0, 1, train=True)
    batch = next(ds)
    assert batch["image"].shape == (4, 32, 32, 3)
    # Authored labels 1..3 arrive 0-based from the reader.
    assert set(np.unique(batch["label"])) <= {0, 1, 2}


def test_eval_split_counts_every_example(authored):
    # 3 classes × 2 + 1 png = 7 validation examples → ceil(7/4) = 2 batches,
    # final batch zero-padded with weight 0 (exact single-pass eval).
    cfg = DataConfig(name="imagenet", data_dir=authored, global_batch_size=4,
                     image_size=32, shuffle_buffer=8, seed=3)
    ds = make_imagenet(cfg, 0, 1, train=False)
    assert ds.cardinality == 2
    it = iter(ds)
    total = sum(float(next(it)["weight"].sum()) for _ in range(ds.cardinality))
    assert total == 7.0
