"""Trace analyzer (core/trace_analysis.py + scripts/analyze_trace.py).

Unit-level: the protobuf wire reader on a hand-encoded XSpace, the HLO
op-map parser, and the classifier. Integration: a REAL CPU-captured
ProfileHook trace of a small train run must break down into categories
summing to >= 90% of the traced window, as text report and as a
schema-versioned trace_summary JSONL event (the ISSUE acceptance bar).
"""

import glob
import os
import subprocess
import sys

from distributed_tensorflow_framework_tpu.core import telemetry
from distributed_tensorflow_framework_tpu.core import trace_analysis as ta
from distributed_tensorflow_framework_tpu.core.config import load_config
from distributed_tensorflow_framework_tpu.train import Trainer

# ------------------------------------------------- synthetic XSpace wire ----
# Hand-encoded protobuf wire format (the same field numbers the reader
# decodes), so the parser is pinned independently of any real profiler run.


def _varint(n: int) -> bytes:
    out = b""
    while True:
        b7 = n & 0x7F
        n >>= 7
        if n:
            out += bytes([b7 | 0x80])
        else:
            return out + bytes([b7])


def _fld(num: int, payload) -> bytes:
    if isinstance(payload, int):  # wire type 0
        return _varint(num << 3 | 0) + _varint(payload)
    return _varint(num << 3 | 2) + _varint(len(payload)) + payload


def _metadata_entry(mid: int, name: str) -> bytes:
    meta = _fld(2, name.encode())                       # XEventMetadata.name
    return _fld(4, _fld(1, mid) + _fld(2, meta))        # XPlane.event_metadata

def _event(mid: int, offset_ps: int, dur_ps: int) -> bytes:
    return _fld(4, _fld(1, mid) + _fld(2, offset_ps) + _fld(3, dur_ps))


def _synthetic_xspace() -> bytes:
    # One executor line: dot 0-400ps, all-reduce 500-800ps, fusion
    # 100-200ps (overlaps the dot), plus a ThunkExecutor wrapper spanning
    # everything (must be filtered, its wait time becoming launch_gap).
    line = (
        _fld(2, b"tf_XLATfrtCpuClient/0") + _fld(3, 0)  # name, timestamp_ns
        + _event(1, 0, 400) + _event(2, 500, 300)
        + _event(3, 100, 100) + _event(4, 0, 800)
    )
    plane = (
        _fld(2, b"/host:CPU")
        + _metadata_entry(1, "dot.11")
        + _metadata_entry(2, "all-reduce.3")
        + _metadata_entry(3, "fusion.7")
        + _metadata_entry(4, "ThunkExecutor::Execute")
        + _fld(3, line)
    )
    return _fld(1, plane)  # XSpace.planes


def test_parse_xspace_wire_format():
    events = ta.parse_xspace(_synthetic_xspace())
    assert {e.name for e in events} == {
        "dot.11", "all-reduce.3", "fusion.7", "ThunkExecutor::Execute"}
    by_name = {e.name: e for e in events}
    assert by_name["all-reduce.3"].start_ps == 500
    assert by_name["all-reduce.3"].duration_ps == 300
    assert all(e.line == "tf_XLATfrtCpuClient/0" for e in events)


def test_analyze_synthetic_breakdown():
    report = ta.analyze(ta.parse_xspace(_synthetic_xspace()))
    # Wrapper span filtered: window is the leaf ops' 0..800ps, busy their
    # union [0,400] + [500,800] = 700ps, gap 100ps.
    assert report["num_events"] == 3
    assert report["window_ps"] == 800
    assert report["busy_ps"] == 700
    assert report["launch_gap_ps"] == 100
    b = report["breakdown"]
    assert b["collectives"]["summed_event_ps"] == 300
    assert b["gemm_conv"]["summed_event_ps"] == 400
    # Proportional attribution keeps categories + gap == window (up to
    # 1 ps of int truncation per category — large against an 800 ps toy
    # window, invisible against a real trace).
    assert report["coverage"] >= 0.99
    fracs = sum(v["fraction_of_window"] for v in b.values())
    assert abs(fracs - 1.0) < 1e-6


def test_hlo_op_map_and_scope_classification():
    hlo = """
HloModule jit_train_step

ENTRY main {
  %dot.11 = f32[64,10]{1,0} dot(a, b), metadata={op_name="jit(train)/dense/dot_general"}
  %mul.5 = f32[10]{0} multiply(x, y), metadata={op_name="jit(train)/optimizer_update/mul"}
  ROOT %add.1 = f32[10]{0} add(%mul.5, c)
}
"""
    hlo_map = ta.parse_hlo_op_map(hlo)
    assert hlo_map["dot.11"][0] == "dot"
    assert "optimizer_update" in hlo_map["mul.5"][1]
    assert ta.classify("mul.5", hlo_map) == "optimizer_update"
    assert ta.classify("dot.11", hlo_map) == "gemm_conv"
    assert ta.classify("all-gather.2", hlo_map) == "collectives"
    assert ta.classify("infeed.1", None) == "infeed"
    assert ta.classify("unknown_fusion", None) == "other_compute"


# ----------------------------------------------------- real CPU capture ----


def _profiled_run(tmp_path):
    cfg = load_config(base={
        "name": "trace-test",
        "mesh": {"data": 8},
        "model": {"name": "lenet5", "num_classes": 10, "dtype": "float32"},
        "data": {"name": "synthetic_images", "global_batch_size": 64,
                 "image_size": 28, "channels": 1},
        "optimizer": {"name": "sgd_momentum", "learning_rate": 0.05},
        "train": {"total_steps": 6, "log_interval": 3,
                  "profile_start": 2, "profile_stop": 4},
    })
    cfg.checkpoint.directory = str(tmp_path / "run")
    cfg.checkpoint.save_interval_steps = 1000
    trainer = Trainer(cfg)
    trainer.train()
    traces = glob.glob(os.path.join(str(tmp_path / "run"), "traces", "**",
                                    "*.xplane.pb"), recursive=True)
    assert traces, "ProfileHook produced no XPlane trace"
    return trainer, traces[0]


def test_analyzer_on_cpu_captured_trace(devices, tmp_path):
    trainer, trace = _profiled_run(tmp_path)

    hlo_path = ta.find_hlo_text(trace)
    assert hlo_path and hlo_path.endswith("train_step.hlo.txt"), (
        "Trainer/ProfileHook did not dump the compiled HLO next to the trace")
    report = ta.analyze_trace_file(trace, open(hlo_path).read())

    # Acceptance bar: the category breakdown accounts for >= 90% of the
    # traced window (categories + launch_gap, honest wall-clock shares).
    assert report["coverage"] >= 0.90, report
    assert report["hlo_map_used"]
    assert report["num_events"] > 0
    fracs = {cat: report["breakdown"][cat]["fraction_of_window"]
             for cat in (*ta.CATEGORIES, ta.GAP)}
    assert sum(fracs.values()) >= 0.90
    assert all(0.0 <= f <= 1.0 for f in fracs.values())
    # A conv net's trace must actually show conv/GEMM time.
    assert report["breakdown"]["gemm_conv"]["summed_event_ps"] > 0

    text = ta.format_report(report)
    for cat in (*ta.CATEGORIES, ta.GAP):
        assert cat in text

    # JSON artifact: a valid schema event joinable by the run's id.
    out = str(tmp_path / "summary.jsonl")
    ta.write_summary_event(report, out, run_id=trainer.run_id)
    evs = list(telemetry.read_events(out, kind=telemetry.KIND_TRACE_SUMMARY))
    assert len(evs) == 1
    ev = evs[0]
    assert telemetry.validate_event(ev) == []
    assert ev["run_id"] == trainer.run_id
    assert ev["metrics"]["coverage"] >= 0.90
    assert set(ev["phases"]) == set((*ta.CATEGORIES, ta.GAP))

    # The CLI wrapper end-to-end: text table on stdout + JSONL artifact.
    cli_out = str(tmp_path / "cli_summary.jsonl")
    proc = subprocess.run(
        [sys.executable, os.path.join(os.path.dirname(ta.__file__),
                                      "..", "..", "scripts",
                                      "analyze_trace.py"),
         os.path.dirname(trace), "--json", cli_out,
         "--run-id", trainer.run_id],
        capture_output=True, text=True, timeout=240,
    )
    assert proc.returncode == 0, proc.stderr
    assert "launch_gap" in proc.stdout
    cli_evs = list(telemetry.read_events(cli_out))
    assert cli_evs and cli_evs[0]["run_id"] == trainer.run_id


def test_trainer_run_emits_joined_telemetry(devices, tmp_path):
    """The tentpole contract: one run id ties events.jsonl, the heartbeat
    file and the trace together."""
    trainer, trace = _profiled_run(tmp_path)
    run_dir = str(tmp_path / "run")

    evs = list(telemetry.read_events(os.path.join(run_dir, "events.jsonl")))
    kinds = [e["kind"] for e in evs]
    assert kinds[0] == telemetry.KIND_RUN_META
    assert telemetry.KIND_TRAIN_STEP in kinds
    assert all(e["run_id"] == trainer.run_id for e in evs)
    step_ev = next(e for e in evs if e["kind"] == telemetry.KIND_TRAIN_STEP)
    assert "loss" in step_ev["metrics"]
    assert "infeed" in step_ev["phases"] and "dispatch" in step_ev["phases"]
    # Per-collective byte counters ride on the step events (profiling was
    # armed, so the build-time lower was tallied).
    assert "collectives" in step_ev
    assert "total_bytes" in step_ev["collectives"]

    import json
    hb = json.load(open(os.path.join(run_dir, "heartbeat.json")))
    assert hb["run_id"] == trainer.run_id
    assert hb["status"] == "finished"
