"""Distributed tracing + flight recorder (core/tracing.py) unit tests.

Covers the pieces the end-to-end drills depend on but cannot isolate:
the SpanContext codec, the Tracer's span emission as ``KIND_SPAN``
telemetry, the clock model under injected wall skew (the analyzer must
reconstruct a causally ordered tree from ±200 ms-skewed per-process
streams — the satellite-3 stitching guarantee), the flight recorder's
bounded ring + dump format, and the ``--spans`` analyzer surface
(trace trees, critical path, Perfetto export).
"""

import json
import os
import tempfile

import pytest

from distributed_tensorflow_framework_tpu.core import cluster, telemetry, tracing
from scripts import analyze_trace


def _spans_from(path: str) -> list[dict]:
    out = []
    with open(path) as fh:
        for line in fh:
            ev = json.loads(line)
            if ev.get("kind") == telemetry.KIND_SPAN:
                out.append(ev)
    return out


# --------------------------------------------------------------- codec --

def test_span_context_round_trips():
    ctx = tracing.SpanContext("abcd1234abcd1234", "ef567890", 1723.456789)
    back = tracing.SpanContext.parse(ctx.encode())
    assert back.trace_id == ctx.trace_id
    assert back.span_id == ctx.span_id
    assert back.sent_at == pytest.approx(ctx.sent_at, abs=1e-6)


def test_span_context_empty_span_id_round_trips():
    # A pure-client root (scripts/load_gen.py) names a trace but no span.
    ctx = tracing.fresh_context(now=10.0)
    back = tracing.SpanContext.parse(ctx.encode())
    assert back.span_id == ""
    assert back.trace_id == ctx.trace_id


@pytest.mark.parametrize("bad", ["", "nocolons", "a:b", "t:s:notafloat",
                                 ":span:1.0"])
def test_span_context_parse_rejects_malformed(bad):
    with pytest.raises(tracing.TraceContextError):
        tracing.SpanContext.parse(bad)


def test_safe_parse_answers_none_not_raise():
    assert tracing.safe_parse(None) is None
    assert tracing.safe_parse("garbage") is None
    assert tracing.safe_parse("t:s:1.0").trace_id == "t"


def test_env_context_reads_the_propagation_var():
    ctx = tracing.fresh_context(now=5.0)
    environ = {tracing.TRACE_CTX_ENV: ctx.encode()}
    got = tracing.env_context(environ)
    assert got is not None and got.trace_id == ctx.trace_id
    assert tracing.env_context({}) is None


def test_worker_env_carries_trace_ctx():
    # core/cluster.py hands the supervisor's attempt context to every
    # gang worker through the same env the discovery triple rides.
    ctx = tracing.fresh_context(now=1.0)
    env = cluster.worker_env(
        {}, coordinator_port=1234, num_processes=2, process_id=1,
        devices_per_proc=1, trace_ctx=ctx.encode())
    assert tracing.env_context(env).trace_id == ctx.trace_id
    untouched = cluster.worker_env(
        {tracing.TRACE_CTX_ENV: ctx.encode()}, coordinator_port=1234,
        num_processes=2, process_id=0, devices_per_proc=1)
    assert tracing.env_context(untouched).trace_id == ctx.trace_id


# -------------------------------------------------------------- tracer --

def test_span_emits_kind_span_event(tmp_path):
    path = str(tmp_path / "events.jsonl")
    writer = telemetry.TelemetryWriter(path, run_id="t")
    tracer = tracing.Tracer(writer, service="svc")
    root = tracer.start("root.op", None, key="val")
    child = tracer.start("child.op", root)
    child.end()
    root.end(status="ok", extra_attr=2)
    writer.close()
    spans = _spans_from(path)
    assert [s["extra"]["name"] for s in spans] == ["child.op", "root.op"]
    c, r = spans
    assert c["extra"]["trace"] == r["extra"]["trace"]
    assert c["extra"]["parent"] == r["extra"]["span"]
    assert r["extra"]["parent"] is None
    assert r["extra"]["service"] == "svc"
    assert r["extra"]["attrs"] == {"key": "val", "extra_attr": 2}
    assert r["metrics"]["dur_ms"] >= 0.0
    # Schema-additive: a span event is a valid dtf-telemetry/1 record.
    assert telemetry.validate_event(r) == []


def test_span_end_is_idempotent(tmp_path):
    path = str(tmp_path / "events.jsonl")
    writer = telemetry.TelemetryWriter(path, run_id="t")
    tracer = tracing.Tracer(writer)
    span = tracer.start("op")
    assert span.end()
    assert span.end() == {}  # crash paths may race the normal end
    writer.close()
    assert len(_spans_from(path)) == 1


def test_emit_span_backfills_from_monotonic_readings(tmp_path):
    path = str(tmp_path / "events.jsonl")
    writer = telemetry.TelemetryWriter(path, run_id="t")
    tracer = tracing.Tracer(writer, service="engine")
    import time
    t0 = time.monotonic()
    ev = tracer.emit_span("engine.batch", None, start_mono=t0 - 0.05,
                          end_mono=t0, rows=4)
    writer.close()
    assert ev["extra"]["name"] == "engine.batch"
    assert ev["metrics"]["dur_ms"] == pytest.approx(50.0, abs=5.0)
    assert tracer.open_spans() == []  # backfill is never left open


def test_open_spans_snapshot_until_ended():
    tracer = tracing.Tracer(None, service="w")
    span = tracer.start("worker.run", None, process=0)
    snaps = tracer.open_spans()
    assert len(snaps) == 1 and snaps[0]["name"] == "worker.run"
    assert snaps[0]["open"] is True
    span.end()
    assert tracer.open_spans() == []


def test_adopt_estimates_clock_offset():
    sender = tracing.Tracer(None, service="sup", skew_s=0.0)
    receiver = tracing.Tracer(None, service="wk", skew_s=0.2)
    span = sender.start("supervisor.attempt")
    receiver.adopt(span.context())
    # Receiver runs 200 ms fast; transmission here is ~instant, so the
    # estimate is dominated by the injected skew.
    assert receiver.offset_s == pytest.approx(0.2, abs=0.05)
    span.end()


# -------------------------------------------- cross-process stitching --

def _two_process_trace(tmp_path, skew_a: float, skew_b: float):
    """Parent span in stream A, child span in stream B, with injected
    wall skews — returns the run dir holding both events files."""
    pa = str(tmp_path / "events.jsonl")
    pb = str(tmp_path / "events-p1.jsonl")
    wa = telemetry.TelemetryWriter(pa, run_id="g")
    wb = telemetry.TelemetryWriter(pb, run_id="g")
    ta = tracing.Tracer(wa, service="supervisor", skew_s=skew_a)
    tb = tracing.Tracer(wb, service="worker0", skew_s=skew_b)
    root = ta.start("supervisor.run")
    attempt = ta.start("supervisor.attempt", root, attempt=1)
    tb.adopt(attempt.context())
    child = tb.start("worker.run", attempt.context())
    child.end()
    attempt.end()
    root.end()
    wa.close()
    wb.close()
    return str(tmp_path)


@pytest.mark.parametrize("skew_a,skew_b", [(0.0, 0.2), (0.2, -0.2)])
def test_skewed_streams_stitch_into_one_ordered_tree(tmp_path, skew_a,
                                                     skew_b):
    """±200 ms wall skew between processes must not break causal order:
    after offset subtraction + the causal clamp, every child starts at
    or after its parent in the reconstructed tree (satellite 3)."""
    run_dir = _two_process_trace(tmp_path, skew_a, skew_b)
    spans = analyze_trace.collect_spans(
        analyze_trace._events_files(run_dir))
    traces = analyze_trace.build_traces(spans)
    assert len(traces) == 1
    t = traces[0]
    by_id = {s["span"]: s for s in t["spans"]}
    assert {s["name"] for s in t["spans"]} == {
        "supervisor.run", "supervisor.attempt", "worker.run"}
    assert len(t["roots"]) == 1
    assert t["roots"][0]["name"] == "supervisor.run"
    for s in t["spans"]:
        parent = by_id.get(s["parent"])
        if parent is not None:
            assert s["t0"] >= parent["t0"] - 1e-9, (s, parent)


def test_trace_tree_text_and_critical_path(tmp_path):
    run_dir = _two_process_trace(tmp_path, 0.0, 0.1)
    spans = analyze_trace.collect_spans(
        analyze_trace._events_files(run_dir))
    traces = analyze_trace.build_traces(spans)
    text = analyze_trace.format_trace_tree(traces[0])
    assert "supervisor.run" in text
    # Child indented under parent, one level per hop.
    lines = text.splitlines()
    run_i = next(i for i, ln in enumerate(lines)
                 if "supervisor.run" in ln)
    worker_i = next(i for i, ln in enumerate(lines) if "worker.run" in ln)
    assert worker_i > run_i
    cp = analyze_trace.critical_path(traces[0])
    assert cp["total"] == pytest.approx(traces[0]["dur_ms"])


def test_unparented_spans_become_roots_not_lost(tmp_path):
    # A crashed process may never emit the parent: its children must
    # surface as extra roots instead of silently disappearing.
    path = str(tmp_path / "events.jsonl")
    writer = telemetry.TelemetryWriter(path, run_id="t")
    tracer = tracing.Tracer(writer, service="r0")
    orphan_parent = tracing.SpanContext("feedface00000000", "dead0001", 0.0)
    span = tracer.start("serve.request", orphan_parent)
    span.end()
    writer.close()
    traces = analyze_trace.build_traces(
        analyze_trace.collect_spans([path]))
    assert len(traces) == 1
    assert traces[0]["roots"][0]["name"] == "serve.request"


def test_perfetto_export_shape(tmp_path):
    run_dir = _two_process_trace(tmp_path, 0.0, 0.05)
    traces = analyze_trace.build_traces(
        analyze_trace.collect_spans(analyze_trace._events_files(run_dir)))
    doc = analyze_trace.perfetto_export(traces)
    events = doc["traceEvents"]
    complete = [e for e in events if e["ph"] == "X"]
    meta = [e for e in events if e["ph"] == "M"]
    assert len(complete) == 3
    assert {e["args"]["name"] for e in meta} == {"supervisor", "worker0"}
    for e in complete:
        assert e["ts"] >= 0 and e["dur"] >= 0
        assert isinstance(e["pid"], int)
    # The whole doc must be JSON-serializable (the export contract).
    json.dumps(doc)


def test_summarize_spans_cli(tmp_path, capsys):
    run_dir = _two_process_trace(tmp_path, 0.0, 0.0)
    perfetto = str(tmp_path / "perfetto.json")
    rc = analyze_trace.main([run_dir, "--spans", "--json", "-"])
    assert rc == 0
    obj = json.loads(capsys.readouterr().out)
    assert obj["schema"] == analyze_trace.TRACE_SPANS_SCHEMA
    assert len(obj["traces"]) == 1
    assert analyze_trace.main(
        [run_dir, "--spans", "--perfetto", perfetto]) == 0
    with open(perfetto) as fh:
        assert json.load(fh)["traceEvents"]
    # No spans anywhere → exit 2, not a traceback.
    empty = tmp_path / "empty"
    empty.mkdir()
    assert analyze_trace.main([str(empty), "--spans"]) == 2


# ------------------------------------------------------ flight recorder --

def test_flight_recorder_ring_is_bounded(tmp_path):
    rec = tracing.FlightRecorder(4, dump_dir=str(tmp_path))
    for i in range(10):
        rec.record({"kind": "x", "i": i})
    path = rec.dump("test")
    with open(path) as fh:
        doc = json.load(fh)
    assert doc["schema"] == tracing.FLIGHTREC_SCHEMA
    assert doc["event_count"] == 4
    assert [e["i"] for e in doc["events"]] == [6, 7, 8, 9]
    assert doc["reason"] == "test"


def test_flight_recorder_rejects_bad_capacity(tmp_path):
    with pytest.raises(ValueError):
        tracing.FlightRecorder(0, dump_dir=str(tmp_path))


def test_flight_recorder_attach_captures_writer_events(tmp_path):
    writer = telemetry.TelemetryWriter(
        str(tmp_path / "events.jsonl"), run_id="t")
    tracer = tracing.Tracer(writer, service="svc")
    rec = tracing.FlightRecorder(
        8, dump_dir=str(tmp_path), tracer=tracer).attach(writer)
    open_span = tracer.start("worker.run")
    done = tracer.start("ckpt.save", open_span)
    done.end()
    path = rec.dump("fault")
    writer.close()
    with open(path) as fh:
        doc = json.load(fh)
    # The ended span rode the listener into the ring; the still-open
    # ancestor appears in open_spans so the dump shows the fault's
    # causal neighborhood even though worker.run never finished.
    assert any((e.get("extra") or {}).get("name") == "ckpt.save"
               for e in doc["events"])
    assert [s["name"] for s in doc["open_spans"]] == ["worker.run"]
    open_span.end()


def test_flight_recorder_default_path_honors_trace_dir(tmp_path,
                                                      monkeypatch):
    monkeypatch.setenv(tracing.TRACE_DIR_ENV, str(tmp_path))
    rec = tracing.FlightRecorder(2)
    assert rec.default_path() == os.path.join(
        str(tmp_path), f"flightrec-{os.getpid()}.json")
    # Explicit dump_dir wins over the env.
    rec2 = tracing.FlightRecorder(2, dump_dir=str(tmp_path / "sub"))
    assert rec2.default_path().startswith(str(tmp_path / "sub"))


def test_flight_recorder_default_path_falls_back_to_writer_dir(
        tmp_path, monkeypatch):
    monkeypatch.delenv(tracing.TRACE_DIR_ENV, raising=False)
    log_dir = tmp_path / "run_logs"
    log_dir.mkdir()
    writer = telemetry.TelemetryWriter(str(log_dir / "events.jsonl"))
    rec = tracing.FlightRecorder(2).attach(writer)
    # No dump_dir, no env var: the dump lands NEXT TO the run's own
    # telemetry, never in the process cwd (= the repo root under pytest).
    assert rec.default_path() == os.path.join(
        str(log_dir), f"flightrec-{os.getpid()}.json")
    writer.close()
    # A stderr-only writer (path None — e.g. a supervisor run without
    # checkpoint.directory) gives no directory clue: the last resort is
    # the system temp dir, NEVER the process cwd.
    bare = telemetry.TelemetryWriter(None)
    rec2 = tracing.FlightRecorder(2).attach(bare)
    assert rec2.default_path().startswith(tempfile.gettempdir())
    bare.close()


def test_repo_root_stays_clean_of_flightrec_dumps():
    # The litter pin: a tier-1 run must leave the repo root free of
    # flightrec-*.json. Every in-repo trigger sets dump_dir, attaches a
    # file-backed writer, or falls back to the system temp dir — there
    # is no cwd fallback left. If this fails, a dump site regressed.
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    litter = [f for f in os.listdir(repo_root)
              if f.startswith("flightrec-") and f.endswith(".json")]
    assert litter == []
