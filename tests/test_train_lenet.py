"""End-to-end minimum slice: LeNet on synthetic data, 8 virtual replicas.

BASELINE.json config 1 ("LeNet-5 on MNIST, single worker, CPU-runnable
smoke test") generalized to 8 fake replicas — exercises mesh, infeed,
jitted step, collectives, hooks and the loop with zero TPU dependency
(SURVEY.md §7 "minimum end-to-end slice").
"""

import numpy as np
import pytest

from distributed_tensorflow_framework_tpu.core.config import load_config
from distributed_tensorflow_framework_tpu.train import Trainer


def lenet_config(**overrides):
    base = {
        "name": "lenet-synthetic",
        "model": {"name": "lenet5", "num_classes": 10, "dtype": "float32"},
        "data": {
            "name": "synthetic_images",
            "global_batch_size": 64,
            "image_size": 28,
            "channels": 1,
        },
        "optimizer": {"name": "sgd_momentum", "learning_rate": 0.05},
        "train": {"total_steps": 30, "log_interval": 10, "seed": 0},
    }
    cfg = load_config(base=base)
    for k, v in overrides.items():
        parts = k.split(".")
        obj = cfg
        for p in parts[:-1]:
            obj = getattr(obj, p)
        setattr(obj, parts[-1], v)
    return cfg


@pytest.mark.parametrize("spmd_mode", ["jit", "shard_map"])
def test_lenet_loss_decreases(devices, spmd_mode, tmp_path):
    cfg = lenet_config(**{"train.spmd_mode": spmd_mode})
    trainer = Trainer(cfg)
    trainer.build()
    first = trainer.evaluate(num_batches=4)
    metrics = trainer.train()
    final = trainer.evaluate(num_batches=4)
    assert np.isfinite(metrics["loss"])
    assert final["eval_loss"] < first["eval_loss"], (
        f"loss did not drop: {first} -> {final}"
    )


def test_dispatch_ahead_backpressure_identical(devices):
    """train.dispatch_ahead bounds the async dispatch queue (the host
    syncs on the oldest in-flight step's metrics) without changing any
    math: a tightly-bounded run must reproduce the unbounded run's final
    loss bit-for-bit, and the backpressure phase must appear in the
    timing metrics."""
    results = {}
    for ahead in (0, 2):
        cfg = lenet_config(**{"train.total_steps": 12,
                              "train.log_interval": 6,
                              "train.dispatch_ahead": ahead})
        trainer = Trainer(cfg)
        results[ahead] = trainer.train()
    assert results[0]["loss"] == results[2]["loss"]
    assert "time_backpressure_ms" in results[2]


def test_bfloat16_infeed(devices):
    """data.image_dtype=bfloat16 (the HBM-bandwidth lever, bench.py) must
    flow through pipeline → infeed → step."""
    cfg = lenet_config(**{"train.total_steps": 5, "data.image_dtype": "bfloat16"})
    trainer = Trainer(cfg)
    metrics = trainer.train()
    assert np.isfinite(metrics["loss"])


@pytest.mark.slow
def test_replica_count_invariance(devices):
    """Sync-DP invariant (SURVEY.md §4): N replicas on global batch B must
    match 1 replica on batch B — the grad mean over a sharded batch equals
    the single-device mean."""
    import jax

    from distributed_tensorflow_framework_tpu.core.config import MeshConfig
    from distributed_tensorflow_framework_tpu.core.mesh import create_mesh
    from distributed_tensorflow_framework_tpu.data.infeed import to_global
    from distributed_tensorflow_framework_tpu.train.step import StepBuilder

    cfg = lenet_config()
    rng = np.random.default_rng(0)
    host = {
        "image": rng.standard_normal((64, 28, 28, 1)).astype(np.float32),
        "label": rng.integers(0, 10, 64).astype(np.int32),
    }
    results = {}
    for n in (1, 8):
        mesh = create_mesh(MeshConfig(data=n), devices=jax.devices()[:n])
        builder = StepBuilder(cfg, mesh)
        batch = to_global(host, mesh)
        state = builder.init_state(0, batch)
        step = builder.make_train_step(batch)
        for _ in range(3):
            state, _ = step(state, batch)
        results[n] = jax.device_get(state.params)

    for a, b in zip(jax.tree.leaves(results[1]), jax.tree.leaves(results[8])):
        np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-5)


def test_jit_and_shard_map_agree(devices):
    """Sync-DP invariant (SURVEY.md §4 numerics parity): the explicit
    shard_map pipeline and the implicit jit pipeline produce the same
    parameters for a BN-free model."""
    import jax

    results = {}
    for mode in ["jit", "shard_map"]:
        cfg = lenet_config(**{"train.spmd_mode": mode, "train.total_steps": 5})
        t = Trainer(cfg)
        t.train()
        results[mode] = jax.device_get(t.state.params)

    flat_a = jax.tree.leaves(results["jit"])
    flat_b = jax.tree.leaves(results["shard_map"])
    for a, b in zip(flat_a, flat_b):
        np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-5)
