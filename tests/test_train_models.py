"""End-to-end train smoke for the non-LeNet workloads (tiny shapes):
Inception-v3 with aux loss, BERT MLM with each attention impl, and BERT
tensor-parallel over the model axis."""

import numpy as np
import pytest

from distributed_tensorflow_framework_tpu.core.config import load_config
from distributed_tensorflow_framework_tpu.train import Trainer


def tiny_bert_base(**model_overrides):
    model = {
        "name": "bert", "vocab_size": 512, "hidden_size": 64,
        "num_layers": 2, "num_heads": 4, "mlp_dim": 128,
        "max_seq_len": 128, "dtype": "float32", "attention_impl": "xla",
    }
    model.update(model_overrides)
    return {
        "name": "bert-tiny",
        "model": model,
        "data": {
            "name": "synthetic_mlm", "global_batch_size": 16, "seq_len": 128,
            "vocab_size": 512,
        },
        "optimizer": {"name": "adamw", "learning_rate": 3e-3,
                      "grad_clip_norm": 1.0},
        "train": {"total_steps": 10, "log_interval": 5, "seed": 1},
    }


@pytest.mark.parametrize("impl", ["xla", "pallas", "ring"])
def test_bert_trains(devices, impl):
    base = tiny_bert_base(attention_impl=impl)
    if impl == "ring":
        base["mesh"] = {"data": 1, "seq": 8}
    cfg = load_config(base=base)
    t = Trainer(cfg)
    metrics = t.train()
    assert np.isfinite(metrics["loss"])
    # vocab 512 → random CE ≈ ln(512) ≈ 6.24; must have moved down.
    assert metrics["loss"] < 6.0, metrics


@pytest.mark.slow
def test_bert_tensor_parallel(devices):
    """model=4 TP: megatron-style sharded QKV/MLP; loss matches DP run."""
    import jax

    results = {}
    for mesh in ({"data": 8}, {"data": 2, "model": 4}):
        base = tiny_bert_base()
        base["mesh"] = mesh
        cfg = load_config(base=base)
        t = Trainer(cfg)
        metrics = t.train()
        results[str(mesh)] = metrics["loss"]
    a, b = results.values()
    np.testing.assert_allclose(a, b, rtol=1e-3)


@pytest.mark.slow
@pytest.mark.slowest
def test_inception_trains(devices):
    cfg = load_config(base={
        "name": "inception-tiny",
        "model": {"name": "inception_v3", "num_classes": 10, "dtype": "float32"},
        "data": {
            "name": "synthetic_images", "global_batch_size": 16,
            "image_size": 96, "channels": 3,
        },
        "optimizer": {"name": "sgd_momentum", "learning_rate": 0.01},
        "train": {"total_steps": 3, "log_interval": 1, "seed": 0},
    })
    t = Trainer(cfg)
    metrics = t.train()
    assert np.isfinite(metrics["loss"])
    assert "aux_loss" in metrics  # aux head active in training


@pytest.mark.slow
@pytest.mark.parametrize("chunk_impl", ["xla", "flash"])
def test_long_ring_config_recipe_builds_and_steps(devices, monkeypatch,
                                                  chunk_impl):
    """configs/bert_long_ring.yaml (the long-context recipe) drives the
    Trainer end to end when scaled down to CPU-mesh size: ring attention
    over seq=8 with remat on. The scaled chunk (32) would dispatch to the
    XLA chain, so the flash variant forces FLASH_CHUNK_MIN=0 to cover the
    Pallas-kernel branch the real 16k config (chunk 2048) takes."""
    from distributed_tensorflow_framework_tpu.parallel import ring

    monkeypatch.setattr(
        ring, "FLASH_CHUNK_MIN", 0 if chunk_impl == "flash" else 10**9)
    cfg = load_config("configs/bert_long_ring.yaml", overrides=[
        "mesh.data=1", "mesh.seq=8",
        "model.vocab_size=512", "model.hidden_size=32",
        "model.num_layers=2", "model.num_heads=2", "model.mlp_dim=64",
        "model.max_seq_len=256",
        "data.vocab_size=512", "data.seq_len=256",
        "data.global_batch_size=4",
        "train.total_steps=4", "train.log_interval=2",
        "checkpoint.directory=",
    ])
    t = Trainer(cfg)
    metrics = t.train()
    assert np.isfinite(metrics["loss"])
