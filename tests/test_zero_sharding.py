"""ZeRO weight-update sharding (optimizer.zero_sharding='shard_map').

ISSUE 9 tentpole: the monolithic shard_map all-reduce is replaced by a
bucketed reduce-scatter in reverse layer order, a per-replica optax
update on 1/(data*fsdp) of the flattened param tree, and a bucketed
all-gather of the UPDATES (params stay replicated master copies).
Pins: f32 parity with the replicated path, the (n, ceil(S/n)) stacked
slot layout with per-device shards at 1/n, the reverse-natural-sorted
bucket issue order (dispatch spy), the shard_opt_state deprecation shim,
checkpoint round-trip of the stacked slots, the int8 error-feedback
composition, and the KIND_ZERO_UPDATE telemetry rollup.
"""

import math

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from distributed_tensorflow_framework_tpu.ckpt import CheckpointManager
from distributed_tensorflow_framework_tpu.core import telemetry
from distributed_tensorflow_framework_tpu.core.config import load_config
from distributed_tensorflow_framework_tpu.core.mesh import create_mesh
from distributed_tensorflow_framework_tpu.data.infeed import to_global
from distributed_tensorflow_framework_tpu.parallel import zero
from distributed_tensorflow_framework_tpu.parallel.sharding import (
    pick_fsdp_dim,
)
from distributed_tensorflow_framework_tpu.train.step import StepBuilder


def _cfg(mesh_axes, zero_mode, *, optimizer=None, parallel=None, train=None):
    opt = {"name": "adam", "learning_rate": 0.01,
           "zero_sharding": zero_mode,
           # Tiny bucket so LeNet splits into several buckets — the
           # overlap structure (not just a single fused collective) is
           # what the parity and dispatch tests exercise.
           "zero_bucket_mb": 0.05}
    opt.update(optimizer or {})
    base = {
        "name": "zero-ud",
        "mesh": mesh_axes,
        "model": {"name": "lenet5", "num_classes": 10, "dtype": "float32"},
        "data": {"name": "synthetic_images", "global_batch_size": 64,
                 "image_size": 28, "channels": 1},
        "optimizer": opt,
        "train": {"total_steps": 5, "log_interval": 5,
                  "spmd_mode": "shard_map", **(train or {})},
    }
    if parallel:
        base["parallel"] = parallel
    return load_config(base=base)


def _batch(mesh):
    rng = np.random.default_rng(0)
    host = {
        "image": rng.standard_normal((64, 28, 28, 1)).astype(np.float32),
        "label": rng.integers(0, 10, 64).astype(np.int32),
    }
    return to_global(host, mesh)


def _run(cfg, steps=3):
    mesh = create_mesh(cfg.mesh)
    builder = StepBuilder(cfg, mesh)
    batch = _batch(mesh)
    state = builder.init_state(0, batch)
    step = builder.make_train_step(batch)
    metrics = {}
    for _ in range(steps):
        state, metrics = step(state, batch)
    return builder, state, jax.device_get(metrics)


# ----------------------------------------------------------- plan unit --
def test_natural_key_orders_digits_numerically():
    paths = ["layer_10/kernel", "layer_2/kernel", "layer_2/bias"]
    ordered = sorted(paths, key=zero.natural_key)
    assert ordered == ["layer_2/bias", "layer_2/kernel", "layer_10/kernel"]


def test_build_plan_reverse_order_and_chunk_math():
    params = {
        "layer_2": {"kernel": np.zeros((7, 3), np.float32)},
        "layer_10": {"kernel": np.zeros((5,), np.float32)},
        "head": {"bias": np.zeros((), np.float32)},
    }
    plan = zero.build_plan(params, n=4, bucket_mb=1e-6)
    # ceil division pads every leaf to n rows; scalars become one element
    # per replica's padded chunk.
    by_path = {lc.path: lc for lc in plan.leaf_chunks}
    assert by_path["layer_2/kernel"].chunk == math.ceil(21 / 4)
    assert by_path["layer_10/kernel"].chunk == math.ceil(5 / 4)
    assert by_path["head/bias"].chunk == 1
    # Tiny bucket budget → one bucket per leaf, issued in REVERSE
    # natural order (deepest layers first, matching backward).
    issue = [lc.path for bucket in plan.buckets for lc in bucket]
    assert issue == sorted(issue, key=zero.natural_key, reverse=True)
    assert plan.num_buckets == 3
    assert plan.shard_elements() == sum(
        lc.chunk for lc in plan.leaf_chunks)


def test_build_plan_accumulates_buckets_by_bytes():
    params = {f"l{i}": np.zeros((64,), np.float32) for i in range(8)}
    # 256 B per leaf; 512 B budget → leaves pair up two per bucket.
    plan = zero.build_plan(params, n=2, bucket_mb=512 / 2**20)
    assert plan.num_buckets == 4
    assert all(len(b) == 2 for b in plan.buckets)


# ------------------------------------------------- parity + slot layout --
def test_f32_parity_zero_vs_replicated(devices):
    _, s_off, m_off = _run(_cfg({"data": 8}, "off"))
    _, s_zero, m_zero = _run(_cfg({"data": 8}, "shard_map"))
    assert np.isfinite(float(m_zero["loss"]))
    np.testing.assert_allclose(
        float(m_off["loss"]), float(m_zero["loss"]), rtol=1e-6)
    # grad_norm comes from shard_global_norm on the zero path — same
    # quantity, computed from disjoint shards.
    np.testing.assert_allclose(
        float(m_off["grad_norm"]), float(m_zero["grad_norm"]), rtol=1e-5)
    # Same data, same mesh, f32 wire: the sharded update must reproduce
    # the replicated trajectory to reduction-order noise (observed
    # ~1e-8 after 3 adam steps).
    for a, b in zip(jax.tree.leaves(jax.device_get(s_off.params)),
                    jax.tree.leaves(jax.device_get(s_zero.params))):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_zero_slots_stacked_and_sharded_one_over_n(devices):
    builder, state, _ = _run(_cfg({"data": 4, "fsdp": 2}, "shard_map"),
                             steps=1)
    plan = builder._zero_plan
    assert plan is not None and plan.n == 8
    valid_chunks = {lc.chunk for lc in plan.leaf_chunks}
    matched = 0
    for leaf in jax.tree.leaves(state.opt_state):
        if getattr(leaf, "ndim", 0) < 2:
            continue
        n, chunk = leaf.shape
        # Every stacked slot is (n, ceil(S/n)) for some param leaf S.
        assert n == 8 and chunk in valid_chunks, leaf.shape
        # Row dim sharded over data×fsdp: each device holds 1/8.
        assert leaf.sharding.spec == P(zero.DATA_AXES)
        shard = leaf.addressable_shards[0].data
        assert shard.shape == (1, chunk)
        matched += 1
    assert matched >= 10, "adam mu+nu slots should all be stacked"
    # Params stay replicated — ZeRO-1/2, not ZeRO-3.
    for leaf in jax.tree.leaves(state.params):
        assert leaf.addressable_shards[0].data.size == leaf.size


def test_zero_slot_rows_detected_for_refold(devices):
    builder, state, _ = _run(_cfg({"data": 8}, "shard_map"), steps=1)
    host = jax.device_get(state)
    assert zero.stacked_rows(host.opt_state, host.params) == 8


# ------------------------------------------------ bucketed issue order --
def test_bucketed_reduce_scatter_issue_order(devices, monkeypatch):
    cfg = _cfg({"data": 8}, "shard_map")
    mesh = create_mesh(cfg.mesh)
    builder = StepBuilder(cfg, mesh)
    batch = _batch(mesh)
    state = builder.init_state(0, batch)
    calls = []
    real = zero._reduce_scatter_bucket

    def spy(mat, axes, *, wire, block_size, paths):
        calls.append(tuple(paths))
        return real(mat, axes, wire=wire, block_size=block_size, paths=paths)

    monkeypatch.setattr(zero, "_reduce_scatter_bucket", spy)
    step = builder.make_train_step(batch)
    state, _ = step(state, batch)  # trace fires the spy once per bucket
    assert len(calls) >= 2, "zero_bucket_mb=0.05 must split LeNet"
    plan = builder._zero_plan
    assert calls == [tuple(lc.path for lc in b) for b in plan.buckets]
    # The flattened issue sequence is reverse natural order — bucket k's
    # reduce-scatter is in program order before the params issued after
    # it, which is what lets XLA overlap it with the backward.
    flat = [p for bucket in calls for p in bucket]
    assert flat == sorted(flat, key=zero.natural_key, reverse=True)


# -------------------------------------------------- config shim + gates --
def test_shard_opt_state_conflict_rejected():
    with pytest.raises(ValueError, match="zero_sharding"):
        _cfg({"data": 4, "fsdp": 2}, "shard_map",
             optimizer={"shard_opt_state": True})


def test_shard_opt_state_maps_to_jit_with_warning(caplog):
    with caplog.at_level("WARNING"):
        cfg = _cfg({"data": 4, "fsdp": 2}, "off",
                   optimizer={"shard_opt_state": True},
                   train={"spmd_mode": "jit"})
    assert cfg.optimizer.zero_sharding == "jit"
    assert any("deprecated" in r.message for r in caplog.records)


def test_zero_shard_map_rejected_under_jit(devices):
    cfg = _cfg({"data": 8}, "shard_map", train={"spmd_mode": "jit"})
    with pytest.raises(ValueError, match="shard_map"):
        StepBuilder(cfg, create_mesh(cfg.mesh))


def test_zero_rejects_lars_and_grad_clip(devices):
    cfg = _cfg({"data": 8}, "shard_map",
               optimizer={"grad_clip_norm": 1.0})
    with pytest.raises(ValueError, match="grad_clip_norm"):
        StepBuilder(cfg, create_mesh(cfg.mesh))
    cfg = _cfg({"data": 8}, "shard_map",
               optimizer={"name": "lars", "learning_rate": 0.1})
    with pytest.raises(ValueError, match="lars"):
        StepBuilder(cfg, create_mesh(cfg.mesh))


def test_bad_zero_mode_rejected():
    with pytest.raises(ValueError, match="zero_sharding"):
        _cfg({"data": 8}, "zero3")


# -------------------------------------------------- checkpoint roundtrip --
def test_zero_opt_state_checkpoint_roundtrip(devices, tmp_path):
    cfg = _cfg({"data": 8}, "shard_map")
    mesh = create_mesh(cfg.mesh)
    builder = StepBuilder(cfg, mesh)
    batch = _batch(mesh)
    state = builder.init_state(0, batch)
    step = builder.make_train_step(batch)
    state, _ = step(state, batch)
    cfg.checkpoint.directory = str(tmp_path / "ck")
    cfg.checkpoint.async_save = False
    mgr = CheckpointManager(cfg.checkpoint, mesh=mesh)
    assert mgr.save(1, state)
    mgr.wait_until_finished()
    restored = mgr.restore(builder.init_state(9, batch))
    mgr.close()
    assert restored is not None
    for a, b in zip(jax.tree.leaves(jax.device_get(state.opt_state)),
                    jax.tree.leaves(jax.device_get(restored.opt_state))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # Restored slots keep the stacked sharded layout.
    stacked = [leaf for leaf in jax.tree.leaves(restored.opt_state)
               if getattr(leaf, "ndim", 0) >= 2]
    assert stacked
    assert all(leaf.addressable_shards[0].data.shape[0] == 1
               for leaf in stacked)


# ------------------------------------------------------- int8 EF compose --
def test_zero_int8_error_feedback(devices):
    cfg = _cfg({"data": 8}, "shard_map",
               parallel={"collective_dtype": "int8",
                         "collective_block_size": 64})
    _, state, metrics = _run(cfg, steps=2)
    assert np.isfinite(float(metrics["loss"]))
    res = jax.tree.leaves(jax.device_get(state.collective_residual))
    assert res and any(np.abs(np.asarray(r)).max() > 0 for r in res)
    # The residual rows live on the replica axis (one EF carry per
    # replica), matching the quantized all-reduce contract.
    for r in jax.tree.leaves(state.collective_residual):
        assert r.shape[0] == 8


# ----------------------------------------------------- telemetry rollup --
def test_zero_update_event_rollup(tmp_path):
    events = str(tmp_path / "events.jsonl")
    writer = telemetry.TelemetryWriter(events)
    params = {"a": np.zeros((64, 64), np.float32),
              "b": np.zeros((128,), np.float32)}
    plan = zero.build_plan(params, n=8, bucket_mb=0.005)
    writer.emit(telemetry.KIND_ZERO_UPDATE, **zero.plan_summary(plan))
    writer.close()
    summary = telemetry.summarize_events(events)
    assert summary["zero"]["shards"] == 8
    assert summary["zero"]["buckets"] == plan.num_buckets
    assert summary["zero"]["rs_wire_bytes"] > 0
    text = telemetry.format_run_summary(summary)
    assert "zero update sharding" in text
    assert "overlap est" in text


def test_plan_summary_wire_bytes_scale_with_dtype():
    params = {"w": np.zeros((256, 16), np.float32)}
    plan = zero.build_plan(params, n=4, bucket_mb=4.0)
    f32 = zero.plan_summary(plan)
    bf16 = zero.plan_summary(plan, wire_dtype="bfloat16")
    i8 = zero.plan_summary(plan, wire_dtype="int8", block_size=64)
    assert f32["wire"] == "float32" and bf16["wire"] == "bfloat16"
    assert bf16["rs_wire_bytes"] * 2 == f32["rs_wire_bytes"]
    # int8 payload is 1/4 of f32 plus per-block scale overhead.
    assert i8["rs_wire_bytes"] < f32["rs_wire_bytes"] / 2
    assert f32["overlap_frac_est"] == 0.0  # single bucket: nothing hidden


# ------------------------------------------------- fsdp dim tie-break --
def test_pick_fsdp_dim_trailing_dim_wins_ties():
    # Square kernels used to depend on dict/scan order; the contract is
    # now explicit: equal-size candidates resolve to the TRAILING dim
    # (the output-features dim for conv/dense kernels).
    assert pick_fsdp_dim((3, 3, 8, 8), 2) == 3
    assert pick_fsdp_dim((8, 8), 4) == 1
    # Still prefers the LARGEST divisible dim when sizes differ.
    assert pick_fsdp_dim((16, 8), 4) == 0
    # Already-sharded dims (per-dim axis entries) are excluded.
    assert pick_fsdp_dim((8, 8), 4, taken=(None, "model")) == 0
    # No divisible dim → -1.
    assert pick_fsdp_dim((3, 5), 4) == -1
