"""tools.autotune — goodput-driven config search for chip windows.

The library behind ``scripts/autotune.py`` (stdlib-only, same layout
discipline as tools/graftcheck): typed SearchSpace specs over the real
config dataclasses (space), an analytic roofline/traffic pruner that
skips configs predicted worse than the incumbent on the binding resource
(model, backed by core/roofline), supervised subprocess trials honoring
the BENCH_WAIT budget and the exit-3 probe_hang taxonomy (runner), the
resumable dtf-autotune-journal/1 trial journal (journal), goodput-
weighted scoring off dtf-run-summary/1 (scoring), the dtf-leaderboard/1
regression pin bench.py reads back (leaderboard), the chip_window plan
compiler that subsumed scripts/chip_window_queue.sh (plan), and the
search loop tying them together (search). docs/PERFORMANCE.md
"Autotuning" is the operator manual.
"""

from tools.autotune.journal import (  # noqa: F401
    JOURNAL_SCHEMA,
    JournalError,
    TrialJournal,
)
from tools.autotune.leaderboard import (  # noqa: F401
    LEADERBOARD_SCHEMA,
    config_digest,
    load_board,
    pin_entry,
    write_best_yaml,
)
from tools.autotune.model import (  # noqa: F401
    Factors,
    TrafficProfile,
    predict_candidate,
    prune_decision,
)
from tools.autotune.plan import (  # noqa: F401
    PlannedTrial,
    compile_chip_window_plan,
    format_plan,
)
from tools.autotune.runner import (  # noqa: F401
    FakeRunner,
    ProbeHangError,
    SubprocessRunner,
    TrialResult,
    TrialRunError,
)
from tools.autotune.scoring import (  # noqa: F401
    RUN_SUMMARY_SCHEMA,
    goodput_frac,
    score_trial,
)
from tools.autotune.search import (  # noqa: F401
    pin_winner,
    run_plan,
    run_space_search,
    trial_id_for,
)
from tools.autotune.space import (  # noqa: F401
    Knob,
    SearchSpace,
    SearchSpaceError,
)
