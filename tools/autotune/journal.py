"""dtf-autotune-journal/1 — the resumable trial journal.

Append-only JSONL, one record per trial state change. The journal is why
a killed chip window (probe hang, preemption, operator ctrl-C) continues
where it stopped instead of re-spending completed trials: on restart the
tuner replays the file, treats every trial whose LAST record is terminal
(``done`` / ``skipped`` / ``failed``) as settled, and re-runs only trials
left ``started`` (killed mid-flight) or never seen. A ``window_abort``
record marks where a probe hang ended the window — the trial it
interrupted stays non-terminal so the next window retries it.
"""

from __future__ import annotations

import json
import os
import time

JOURNAL_SCHEMA = "dtf-autotune-journal/1"

# Terminal statuses: the trial consumed its decision and must not re-run
# on resume. "started" and "window_abort" are non-terminal by design.
TERMINAL_STATUSES = ("done", "skipped", "failed")


class JournalError(RuntimeError):
    """A journal line that is not valid JSON or carries the wrong schema
    tag. Raised by TrialJournal.replay (strict mode) and caught by the
    scripts/autotune.py CLI, which refuses to resume from a corrupt
    journal rather than silently re-running paid-for trials."""


class TrialJournal:
    def __init__(self, path: str):
        self.path = path

    def replay(self, strict: bool = True) -> dict[str, dict]:
        """{trial_id: last record} from the journal (empty if absent)."""
        state: dict[str, dict] = {}
        if not os.path.exists(self.path):
            return state
        with open(self.path) as fh:
            for lineno, line in enumerate(fh, 1):
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError as e:
                    if strict:
                        raise JournalError(
                            f"{self.path}:{lineno}: not JSON ({e})") from e
                    continue
                if rec.get("schema") != JOURNAL_SCHEMA:
                    if strict:
                        raise JournalError(
                            f"{self.path}:{lineno}: schema "
                            f"{rec.get('schema')!r} != {JOURNAL_SCHEMA!r}")
                    continue
                trial = rec.get("trial")
                if trial:
                    state[trial] = rec
        return state

    def settled(self) -> dict[str, dict]:
        """Trials whose last status is terminal — skipped on resume."""
        return {t: rec for t, rec in self.replay().items()
                if rec.get("status") in TERMINAL_STATUSES}

    def record(self, trial: str, status: str, **fields) -> dict:
        """Append one state change (fsync'd — the journal must survive
        the very kill it exists to recover from)."""
        rec = {"schema": JOURNAL_SCHEMA, "trial": trial, "status": status,
               "t": time.time(), **fields}
        os.makedirs(os.path.dirname(os.path.abspath(self.path)),
                    exist_ok=True)
        with open(self.path, "a") as fh:
            fh.write(json.dumps(rec) + "\n")
            fh.flush()
            os.fsync(fh.fileno())
        return rec
