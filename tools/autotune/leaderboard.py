"""dtf-leaderboard/1 — the regression-pinned incumbent board.

``configs/leaderboard.json`` holds one entry per workload: the winning
config (as the override dict the tuner searched), a content digest of
that config, the goodput-weighted score it earned, the roofline verdict
and chip it was measured on, and provenance (run id, journal path).
bench.py reads the board on every headline run and flags a regression
when the fresh number undershoots the pinned incumbent by more than the
entry's margin (bench._check_leaderboard); scripts/autotune.py is the
only writer. The digest is re-verified on read — an entry whose digest
doesn't match its own config dict was edited by hand and can't serve as
a pin.
"""

from __future__ import annotations

import hashlib
import json
import os

LEADERBOARD_SCHEMA = "dtf-leaderboard/1"


def config_digest(config: dict) -> str:
    """Content digest of a config-override dict: sha256 over canonical
    JSON (sorted keys, no whitespace), truncated for legibility. The same
    function pins entries at write time and verifies them at read time
    (bench.py), so a hand-edited board is detectable."""
    blob = json.dumps(config, sort_keys=True, separators=(",", ":"))
    return "sha256:" + hashlib.sha256(blob.encode("utf-8")).hexdigest()[:16]


def load_board(path: str) -> dict:
    """Parse the board, or an empty one when the file doesn't exist yet."""
    try:
        with open(path) as fh:
            board = json.load(fh)
    except (OSError, ValueError):
        return {"schema": LEADERBOARD_SCHEMA, "entries": {}}
    board.setdefault("schema", LEADERBOARD_SCHEMA)
    board.setdefault("entries", {})
    return board


def pin_entry(path: str, workload: str, *, config: dict, score: float,
              unit: str, bound: str | None, chip: str | None,
              provenance: dict, regression_margin: float = 0.05) -> dict:
    """Install/replace the incumbent for ``workload`` and rewrite the
    board atomically (tmp + rename — a crashed tuner must not leave a
    half-written pin for bench.py to choke on). Returns the new entry."""
    board = load_board(path)
    entry = {
        "config": dict(config),
        "config_digest": config_digest(config),
        "score": round(float(score), 4),
        "unit": unit,
        "bound": bound,
        "chip": chip,
        "provenance": dict(provenance),
        "regression_margin": float(regression_margin),
    }
    board["entries"][workload] = entry
    tmp = path + ".tmp"
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(tmp, "w") as fh:
        json.dump(board, fh, indent=1, sort_keys=True)
        fh.write("\n")
    os.replace(tmp, path)
    return entry


def _yaml_scalar(value) -> str:
    if isinstance(value, bool):
        return "true" if value else "false"
    if value is None or value == "":
        return '""'
    return str(value)


def write_best_yaml(path: str, workload: str, overrides: dict,
                    *, score: float, digest: str) -> None:
    """``configs/best_<workload>.yaml``: the winning overrides as a YAML
    fragment in the repo's ``section.field`` config layout, with the
    provenance in a comment header. Overrides arrive keyed by dotted
    path ("precision.activation_dtype") and are grouped by section."""
    sections: dict[str, dict[str, object]] = {}
    for dotted, value in sorted(overrides.items()):
        section, _, field = dotted.partition(".")
        sections.setdefault(section, {})[field] = value
    lines = [
        f"# Autotune winner for {workload} — written by scripts/autotune.py.",
        f"# goodput-weighted score {round(float(score), 4)}, "
        f"config digest {digest}.",
        "# Apply on top of the workload's base config "
        "(configs/leaderboard.json is the pin).",
    ]
    for section, fields in sorted(sections.items()):
        lines.append(f"{section}:")
        for field, value in sorted(fields.items()):
            lines.append(f"  {field}: {_yaml_scalar(value)}")
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "w") as fh:
        fh.write("\n".join(lines) + "\n")
