"""Analytic traffic model: predict a candidate's roofline position
BEFORE spending a run on it.

The incumbent's measured artifacts (XLA cost-model flops, memory_analysis
footprint, CollectiveTally wire bytes, opt_state_bytes_per_chip — all
already on every bench row) give a TrafficProfile. Each knob value
carries analytic multipliers on the four traffic components (flops, HBM
bytes, wire bytes, optimizer-state bytes) relative to that knob's
baseline value; a candidate's predicted traffic is the incumbent's
scaled by the product of its knobs' relative factors. core/roofline
turns predicted traffic into a step-time floor per resource, and the
pruning rule compares candidates to the incumbent ON THE BINDING
RESOURCE: a candidate whose predicted rate is more than ``prune_margin``
below the incumbent's predicted rate is skipped with the numbers logged.
Both sides of the comparison go through the same model, so systematic
model error divides out; the margin absorbs the rest.

Factor values are analytic-with-measured-anchors, documented inline
(PERF_NOTES.md / docs/PERFORMANCE.md are the sources). A (path, value)
absent from the table is neutral (factor 1.0) — the model must never
prune on a knob it has no opinion about.
"""

from __future__ import annotations

import dataclasses

from distributed_tensorflow_framework_tpu.core import roofline


@dataclasses.dataclass(frozen=True)
class Factors:
    """Multipliers on the four traffic components (1.0 = unchanged)."""

    flops: float = 1.0
    hbm: float = 1.0
    wire: float = 1.0
    opt: float = 1.0


@dataclasses.dataclass
class TrafficProfile:
    """The incumbent's measured per-step traffic (from its bench row)."""

    chip: str
    n_chips: int = 1
    flops_per_step: float = 0.0
    hbm_bytes_per_step: float = 0.0   # memory_analysis arg+out+temp
    wire_bytes_per_step: float = 0.0  # CollectiveTally total
    opt_state_bytes: float = 0.0      # bench opt_state_bytes_per_chip
    examples_per_step: float = 1.0


# (knob path, value) → Factors. Sources: the precision-pack A/B rows
# (docs/PERFORMANCE.md "Flipping the bound"), the EQuARX-style wire
# ratios (int8 ≈ 3.9x fewer wire bytes), and the ZeRO argument that
# sharded optimizer state divides its HBM traffic by the data-parallel
# width (applied via the ``opt`` component, resolved per-profile).
TRAFFIC_FACTORS: dict[str, dict[object, Factors]] = {
    "precision.activation_dtype": {
        # bf16 activations halve the activation stream; params/grads stay
        # f32, so the whole-step HBM byte count lands near 0.55x.
        "bf16": Factors(hbm=0.55),
    },
    "precision.fused_update": {
        # Fused AdamW update removes one full read+write pass over the
        # param tree (~10% of a ResNet step's bytes).
        True: Factors(hbm=0.90),
    },
    "precision.matmul_dtype": {
        # int8 MXU matmuls shrink the streamed operand bytes but add
        # quantize/dequantize flops.
        "int8": Factors(hbm=0.85, flops=1.05),
    },
    "parallel.collective_dtype": {
        "bfloat16": Factors(wire=0.5),
        "int8": Factors(wire=0.26),  # EQuARX-style ≈3.9x wire reduction
    },
    "optimizer.zero_sharding": {
        # Resolved against profile.n_chips in predict_candidate: each
        # chip keeps 1/n of the optimizer state.
        "shard_map": Factors(opt=0.0),  # sentinel; see _resolve_factors
    },
    "model.remat_policy": {
        # Full-replay remat trades ~30% more flops for not streaming
        # saved activations (PERF_NOTES round 2: 78.7→84.5 FLOP/byte,
        # net loss on an HBM-bound step — exactly what pruning catches).
        "full": Factors(flops=1.30, hbm=0.80),
    },
}


def _resolve_factors(path: str, value: object,
                     profile: TrafficProfile) -> Factors:
    table = TRAFFIC_FACTORS.get(path, {})
    f = table.get(value)
    if f is None:
        return Factors()
    if path == "optimizer.zero_sharding" and f.opt == 0.0:
        return Factors(flops=f.flops, hbm=f.hbm, wire=f.wire,
                       opt=1.0 / max(1, profile.n_chips))
    return f


def predict_candidate(profile: TrafficProfile,
                      overrides: dict[str, object],
                      baseline: dict[str, object]) -> roofline.RooflinePrediction:
    """Roofline step-time floor for a candidate's override dict, scaling
    the incumbent profile by each knob's factor RELATIVE to the baseline
    value of that knob (so the incumbent predicts onto itself exactly)."""
    flops = profile.flops_per_step
    hbm = profile.hbm_bytes_per_step
    wire = profile.wire_bytes_per_step
    opt = profile.opt_state_bytes
    for path, value in overrides.items():
        cand = _resolve_factors(path, value, profile)
        base = _resolve_factors(path, baseline.get(path), profile)
        flops *= cand.flops / base.flops
        hbm *= cand.hbm / base.hbm
        wire *= cand.wire / base.wire
        opt *= cand.opt / base.opt
    total_bytes = roofline.traffic_bytes(None, wire, opt) + hbm
    return roofline.predict(profile.chip, flops, total_bytes,
                            n_chips=profile.n_chips)


def prune_decision(profile: TrafficProfile, overrides: dict[str, object],
                   baseline: dict[str, object],
                   prune_margin: float) -> tuple[bool, str, dict]:
    """(skip, reason, detail) for one candidate.

    Predicted rate = examples_per_step / predicted step-time floor, for
    candidate and incumbent through the SAME model; skip when the
    candidate undershoots by more than ``prune_margin`` on the binding
    resource (the max() term inside roofline.predict IS the binding
    resource's time).
    """
    cand = predict_candidate(profile, overrides, baseline)
    incumbent = predict_candidate(profile, baseline, baseline)
    cand_rate = profile.examples_per_step / cand.sec_per_step \
        if cand.sec_per_step else 0.0
    inc_rate = profile.examples_per_step / incumbent.sec_per_step \
        if incumbent.sec_per_step else 0.0
    detail = {
        "predicted_rate": round(cand_rate, 2),
        "incumbent_rate": round(inc_rate, 2),
        "bound": cand.bound,
        "ridge_source": cand.ridge_source,
        "sec_compute": cand.sec_compute,
        "sec_hbm": cand.sec_hbm,
    }
    if inc_rate <= 0:
        return False, "no incumbent prediction — running", detail
    ratio = cand_rate / inc_rate
    detail["vs_incumbent"] = round(ratio, 4)
    if ratio < 1.0 - prune_margin:
        return True, (
            f"predicted {cand_rate:.1f} vs incumbent {inc_rate:.1f} "
            f"({(1 - ratio) * 100:.1f}% worse on {cand.bound}, margin "
            f"{prune_margin * 100:.0f}%) — pruned"), detail
    return False, (
        f"predicted {cand_rate:.1f} vs incumbent {inc_rate:.1f} "
        f"(within margin) — running"), detail
