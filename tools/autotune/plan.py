"""The chip_window plan: scripts/chip_window_queue.sh compiled to data.

`autotune.py --plan chip_window` turns the round-5 measurement queue
(§0–§17, PERF_NOTES.md round-4 closeout) into a prioritized trial list
the search loop can journal, resume and supervise like any other trial
set. Priorities, per the queue's own rules:

  1. §0/§0b preflights — a graftcheck finding or a probe hang refuses
     to spend the window at all (exit 1 / exit 3 respectively);
  2. §1 — re-validate BENCH_r02 (the last good chip number, 2513
     img/s/chip) before anything else, so a silent regression is caught
     while the whole window is still ahead;
  3. §13 precision ladder — the highest-information dial (the "flipping
     the bound" question);
  4. §7–§12, §14–§17 in section order;
  5. the remaining round-5 backlog (§2–§6) at the tail.

Multi-process arms (serve/fleet/decode/infeed) keep their original
orchestration — background server, load_gen, SIGTERM drain, analyze — as
single composite trials (bash -c), byte-for-byte the recipes the queue
script ran, so the A/B identities the window has been tracking survive
the compilation.
"""

from __future__ import annotations

import dataclasses
import sys

PY = sys.executable or "python"


@dataclasses.dataclass(frozen=True)
class PlannedTrial:
    """One queue arm: ``section`` is the chip_window_queue § it came
    from, ``gate`` names a trial that must succeed first (numerics
    verifies, exports), ``kind`` separates preflights (whose failure
    aborts the window) from ordinary trials."""

    section: str
    label: str
    argv: tuple
    env: tuple = ()          # ((name, value), ...) — hashable
    gate: str = ""
    kind: str = "trial"      # "preflight" | "trial"

    def env_dict(self) -> dict[str, str]:
        return dict(self.env)


def _bench(section, label, gate="", **env) -> PlannedTrial:
    return PlannedTrial(section, label, (PY, "bench.py"),
                        tuple((k, str(v)) for k, v in env.items()),
                        gate=gate)


def _script(section, label, argv, gate="", **env) -> PlannedTrial:
    return PlannedTrial(section, label, tuple(argv),
                        tuple((k, str(v)) for k, v in env.items()),
                        gate=gate)


def _composite(section, label, script, gate="") -> PlannedTrial:
    """A multi-process arm as one bash trial (original queue recipe)."""
    return PlannedTrial(section, label, ("bash", "-c", script), gate=gate)


_SERVE_AB = """
set -u
rm -rf /tmp/chipq_serve/artifact/serve_logs
python -m distributed_tensorflow_framework_tpu.cli.serve \\
    --artifact /tmp/chipq_serve/artifact \\
    --set serve.port=0 --set serve.max_batch_size={batch} \\
    --set serve.max_wait_ms=5 > /tmp/chipq_serve_{label}.log 2>&1 &
pid=$!
for _ in $(seq 120); do
  [ -f /tmp/chipq_serve/artifact/serve_logs/endpoint.json ] && break
  sleep 1
done
python scripts/load_gen.py \\
    --endpoint /tmp/chipq_serve/artifact/serve_logs/endpoint.json \\
    --requests 512 --concurrency 32 --rate 200 --mode both \\
    --out SERVE_BENCH_{label}.json
rc=$?
kill -TERM "$pid" 2>/dev/null
wait "$pid"
python scripts/analyze_trace.py /tmp/chipq_serve/artifact/serve_logs/events.jsonl
exit $rc
"""

_FLEET_AB = """
set -u
python -m distributed_tensorflow_framework_tpu.cli.fleet \\
    --artifact /tmp/chipq_serve/artifact --replicas 3 \\
    --set serve.log_dir=/tmp/chipq_fleet \\
    --set serve.max_batch_size=8 --set serve.max_wait_ms=5 \\
    > /tmp/chipq_fleet.log 2>&1 &
pid=$!
for _ in $(seq 240); do
  [ -f /tmp/chipq_fleet/endpoint.json ] && break
  sleep 1
done
python scripts/load_gen.py \\
    --endpoint /tmp/chipq_fleet/endpoint.json \\
    --requests 512 --concurrency 32 --rate 200 --mode both \\
    --out SERVE_BENCH_fleet.json
rc=$?
kill -TERM "$pid" 2>/dev/null
wait "$pid"
python scripts/analyze_trace.py /tmp/chipq_fleet/events.jsonl
exit $rc
"""

_DECODE_AB = """
set -u
python -m distributed_tensorflow_framework_tpu.cli.serve \\
    --artifact /tmp/chipq_decode/artifact \\
    --set serve.port=0 \\
    --set serve.log_dir=/tmp/chipq_decode/logs_{label} \\
    --set decode.enabled=true --set decode.max_len=128 \\
    --set decode.page_size=16 --set decode.num_pages=256 \\
    --set decode.max_streams=8 --set decode.max_new_tokens=96 \\
    --set decode.stream_interval=8 {extra} \\
    > /tmp/chipq_decode_{label}.log 2>&1 &
pid=$!
for _ in $(seq 120); do
  [ -f /tmp/chipq_decode/logs_{label}/endpoint.json ] && break
  sleep 1
done
python scripts/load_gen.py \\
    --endpoint /tmp/chipq_decode/logs_{label}/endpoint.json \\
    --mode decode --requests 64 --concurrency 8 \\
    --max-new-tokens 96 --out DECODE_BENCH_{label}.json
rc=$?
kill -TERM "$pid" 2>/dev/null
wait "$pid"
exit $rc
"""

_INFEED_AB = """
set -u
rm -rf /tmp/chipq_infeed/{label}
python train.py --config configs/bert_base_mlm.yaml \\
    --set data.name=synthetic_mlm --set train.total_steps=100 \\
    --set train.log_interval=25 --set train.eval_steps=0 \\
    --set train.eval_interval=0 \\
    --set model.hidden_size=256 --set model.num_layers=4 \\
    --set model.num_heads=4 --set model.mlp_dim=1024 \\
    --set model.max_seq_len=512 --set data.seq_len=512 \\
    --set data.global_batch_size=32 \\
    --set checkpoint.directory=/tmp/chipq_infeed/{label} {extra} || exit $?
python scripts/analyze_trace.py /tmp/chipq_infeed/{label}
"""

_GANG_PROBE = (
    "import sys\n"
    "from distributed_tensorflow_framework_tpu.core import cluster\n"
    "ok, detail = cluster.probe_gang(procs=2, devices_per_proc=2)\n"
    "if not ok:\n"
    "    print(detail[-800:], file=sys.stderr)\n"
    "sys.exit(0 if ok else 1)\n")


def _gang_run(workdir, procs, dev, ckpt) -> tuple:
    return (PY, "scripts/train_cluster.py",
            "--procs", str(procs), "--devices-per-proc", str(dev),
            "--workdir", workdir, "--max-attempts", "1", "--",
            "--config", "configs/lenet_mnist.yaml",
            "--set", "train.total_steps=200", "--set",
            "train.log_interval=50", "--set", "train.eval_steps=0",
            "--set", "train.eval_interval=0",
            "--set", "data.global_batch_size=32", "--set", "mesh.data=-1",
            "--set", f"checkpoint.directory={ckpt}")


_SERVE_TRAIN = (
    PY, "train.py", "--config", "configs/lenet_mnist.yaml",
    "--set", "data.name=synthetic_images", "--set", "train.total_steps=30",
    "--set", "checkpoint.directory=/tmp/chipq_serve/ckpt",
    "--set", "checkpoint.save_interval_steps=30",
    "--set", "checkpoint.async_save=false")

_SERVE_EXPORT = (
    PY, "-m", "distributed_tensorflow_framework_tpu.cli.export",
    "--config", "configs/lenet_mnist.yaml",
    "--set", "data.name=synthetic_images",
    "--set", "checkpoint.directory=/tmp/chipq_serve/ckpt",
    "--set", "serve.allow_reshard=true",
    "--output", "/tmp/chipq_serve/artifact")

_DECODE_SHAPES = (
    "--set", "data.name=synthetic_mlm",
    "--set", "model.hidden_size=256", "--set", "model.num_layers=4",
    "--set", "model.num_heads=4", "--set", "model.mlp_dim=1024",
    "--set", "model.max_seq_len=128", "--set", "data.seq_len=128")

_DECODE_TRAIN = (
    (PY, "train.py", "--config", "configs/bert_base_mlm.yaml")
    + _DECODE_SHAPES
    + ("--set", "train.total_steps=30",
       "--set", "data.global_batch_size=32",
       "--set", "train.eval_steps=0", "--set", "train.eval_interval=0",
       "--set", "checkpoint.directory=/tmp/chipq_decode/ckpt",
       "--set", "checkpoint.save_interval_steps=30",
       "--set", "checkpoint.async_save=false"))

_DECODE_EXPORT = (
    (PY, "-m", "distributed_tensorflow_framework_tpu.cli.export",
     "--config", "configs/bert_base_mlm.yaml")
    + _DECODE_SHAPES
    + ("--set", "checkpoint.directory=/tmp/chipq_decode/ckpt",
       "--set", "serve.allow_reshard=true",
       "--output", "/tmp/chipq_decode/artifact"))


def compile_chip_window_plan() -> list[PlannedTrial]:
    """The full prioritized window (see module docstring for the order)."""
    trials: list[PlannedTrial] = []

    # §0/§0b preflights: refuse to spend the window on a tree graftcheck
    # rejects or a chip whose probe hangs (exit 3 → window abort).
    trials.append(PlannedTrial(
        "0", "graftcheck", (PY, "scripts/graftcheck.py"),
        (("JAX_PLATFORMS", "cpu"),), kind="preflight"))
    trials.append(PlannedTrial(
        "0b", "probe", (PY, "bench.py"), (("BENCH_PROBE_ONLY", "1"),),
        kind="preflight"))

    # §1: re-validate BENCH_r02 (the last good number) FIRST.
    trials.append(_bench("1", "resnet"))

    # §13 precision ladder — the priority dial.
    trials.append(_bench("13", "prec-f32", BENCH_PRECISION="f32"))
    trials.append(_bench("13", "prec-bf16", BENCH_PRECISION="bf16"))
    trials.append(_bench("13", "prec-bf16-fused",
                         BENCH_PRECISION="bf16_fused"))
    trials.append(_bench("13", "prec-bf16-int8",
                         BENCH_PRECISION="bf16_int8"))

    # §7 whole-K takeover bands: numerics verify gates each pair.
    for seq, bs in ((2048, 16), (4096, 8)):
        verify = f"wk-verify-{seq}"
        trials.append(_script(
            "7", verify, (PY, "scripts/verify_fused_bwd.py", str(seq))))
        trials.append(_bench(
            "7", f"wk{seq}-fused", gate=verify, BENCH_WORKLOAD="bert",
            BENCH_ATTN="pallas", BENCH_SEQ=seq, BENCH_BS=bs))
        trials.append(_bench(
            "7", f"wk{seq}-two", gate=verify, BENCH_WORKLOAD="bert",
            BENCH_ATTN="pallas", BENCH_SEQ=seq, BENCH_BS=bs,
            FLASH_FUSED_WHOLE_K_MIN=1000000000))

    # §8 pipeline-schedule A/B (pp-sanity re-probes the tunnel cheap).
    trials.append(_bench("8", "pp-sanity"))
    for sched in ("gpipe", "1f1b", "interleaved"):
        trials.append(_bench(
            "8", f"pp-{sched}", BENCH_WORKLOAD="bert", BENCH_PP=4,
            BENCH_MICRO=8, BENCH_SCHEDULE=sched))

    # §9 quantized-collective wire A/B.
    for mode in ("f32", "bf16", "int8"):
        trials.append(_bench("9", f"coll-{mode}", BENCH_COLLECTIVE=mode))

    # §10 serving A/B: train → export gate the two standing-server arms.
    trials.append(_composite(
        "10", "serve-clean", "rm -rf /tmp/chipq_serve"))
    trials.append(_script("10", "serve-train", _SERVE_TRAIN,
                          gate="serve-clean"))
    trials.append(_script("10", "serve-export", _SERVE_EXPORT,
                          gate="serve-train"))
    for label, batch in (("batched", 8), ("unbatched", 1)):
        trials.append(_composite(
            "10", f"serve-{label}",
            _SERVE_AB.format(label=label, batch=batch),
            gate="serve-export"))

    # §11 ZeRO weight-update sharding A/B.
    for mode in ("off", "shard_map"):
        trials.append(_bench("11", f"zero-{mode}", BENCH_ZERO=mode))

    # §12 HBM memory close-out.
    trials.append(_bench("12", "mem-headline",
                         BENCH_JSONL="/tmp/chipq_mem_events.jsonl"))
    trials.append(_script(
        "12", "mem-summary",
        (PY, "scripts/analyze_trace.py", "/tmp/chipq_mem_events.jsonl",
         "--json", "-"), gate="mem-headline"))

    # §14 fleet-vs-single serving A/B (reuses §10's artifact).
    trials.append(_composite("14", "serve-fleet", _FLEET_AB,
                             gate="serve-export"))

    # §15 gang A/B, gated on its own probe_gang preflight.
    trials.append(_script("15", "gang-probe", (PY, "-c", _GANG_PROBE)))
    trials.append(_composite("15", "gang-clean", "rm -rf /tmp/chipq_gang",
                             gate="gang-probe"))
    trials.append(_script(
        "15", "gang-1p",
        _gang_run("/tmp/chipq_gang/w1", 1, 4, "/tmp/chipq_gang/ck1"),
        gate="gang-clean"))
    trials.append(_script(
        "15", "gang-2p",
        _gang_run("/tmp/chipq_gang/w2", 2, 2, "/tmp/chipq_gang/ck2"),
        gate="gang-clean"))
    trials.append(_script(
        "15", "gang-ab",
        (PY, "scripts/analyze_trace.py", "/tmp/chipq_gang/ck1"),
        gate="gang-1p"))
    trials.append(_script(
        "15", "gang-ab-2p",
        (PY, "scripts/analyze_trace.py", "/tmp/chipq_gang/ck2"),
        gate="gang-2p"))

    # §16 decode A/Bs: artifact build gates the three standing-server arms.
    trials.append(_composite("16", "decode-clean",
                             "rm -rf /tmp/chipq_decode"))
    trials.append(_script("16", "decode-train", _DECODE_TRAIN,
                          gate="decode-clean"))
    trials.append(_script("16", "decode-export", _DECODE_EXPORT,
                          gate="decode-train"))
    for label, extra in (
            ("continuous", "--set decode.scheduler=continuous"),
            ("static", "--set decode.scheduler=static"),
            ("int8", "--set decode.scheduler=continuous "
                     "--set decode.kv_dtype=int8")):
        trials.append(_composite(
            "16", f"decode-{label}",
            _DECODE_AB.format(label=label, extra=extra),
            gate="decode-export"))

    # §17 infeed A/B: packing + shard-mode dials.
    for label, extra in (
            ("unpacked", "--set data.pack_factor=1"),
            ("packed", "--set data.pack_factor=4"),
            ("block", "--set data.pack_factor=4 "
                      "--set data.shard_mode=block"),
            ("stride", "--set data.pack_factor=4 "
                       "--set data.shard_mode=stride")):
        trials.append(_composite(
            "17", f"infeed-{label}",
            _INFEED_AB.format(label=label, extra=extra)))

    # Round-5 backlog tail (§2–§6), original order.
    trials.append(_bench("2", "bert-base", BENCH_WORKLOAD="bert"))
    trials.append(_bench("2", "bert-fqkv", BENCH_WORKLOAD="bert",
                         BENCH_FUSED_QKV=1))
    for q in (512, 1024):
        trials.append(_bench(
            "3", f"tile-{q}-1024", BENCH_WORKLOAD="bert",
            BENCH_ATTN="pallas", BENCH_SEQ=8192, BENCH_BS=4,
            FLASH_BLOCK_Q_KB=q, FLASH_BLOCK_K_KB=1024, FLASH_FUSED_BWD=0))
    trials.append(_script(
        "4", "crossover",
        (PY, "scripts/bench_chunk_crossover.py", "256", "512", "1024",
         "2048", "4096")))
    trials.append(_script(
        "4b", "fused-bwd-verify", (PY, "scripts/verify_fused_bwd.py",
                                   "8192")))
    trials.append(_bench(
        "4b", "fused-bwd", gate="fused-bwd-verify", BENCH_WORKLOAD="bert",
        BENCH_ATTN="pallas", BENCH_SEQ=8192, BENCH_BS=4,
        FLASH_FUSED_BWD=1))
    trials.append(_bench("4c", "bert-accum4", BENCH_WORKLOAD="bert",
                         BENCH_ACCUM=4))
    trials.append(_bench("5", "trace", BENCH_TRACE="/tmp/bench_trace"))
    trials.append(_bench("6", "inception", BENCH_WORKLOAD="inception"))
    return trials


def format_plan(trials: list[PlannedTrial]) -> str:
    """The --dry-run rendering: one line per trial, parseable
    (``NNN §SEC LABEL [kind] [gate=...] -- ENV.. ARGV..``)."""
    lines = []
    for i, t in enumerate(trials, 1):
        envs = " ".join(f"{k}={v}" for k, v in t.env)
        # Composite arms carry multi-line ``bash -c`` scripts; one trial
        # must stay one parseable line, so collapse to the first line.
        args = [a.splitlines()[0] + " \\..." if "\n" in a else a
                for a in t.argv]
        cmd = " ".join(args[:4]) + (" ..." if len(args) > 4 else "")
        bits = [f"{i:03d}", f"§{t.section}", t.label, f"[{t.kind}]"]
        if t.gate:
            bits.append(f"gate={t.gate}")
        bits.append("--")
        if envs:
            bits.append(envs)
        bits.append(cmd)
        lines.append(" ".join(bits))
    return "\n".join(lines)
