"""Trial runners: supervised subprocesses + the deterministic fake.

SubprocessRunner drives bench.py / scripts/load_gen.py exactly the way
scripts/chip_window_queue.sh used to: one child per trial, the BENCH_WAIT
retry budget forwarded, the result read from the BENCH_OUT file (never
regexed out of warning-polluted stdout — the BENCH_r03–r05 parse-loss
fix), and the exit-3 ``probe_hang`` taxonomy honored — a hung probe
raises ProbeHangError, which aborts the WINDOW (the search journal stays
resumable) rather than failing the search.

FakeRunner serves the CPU-only test tier: a spec table mapping trial ids
to canned payloads/exit codes (plus optional per-trial sleeps, so kill/
resume drills can interrupt a window deterministically) exercises
pruning, journaling, scoring and leaderboard pinning without a chip.
"""

from __future__ import annotations

import dataclasses
import json
import os
import subprocess
import sys
import tempfile
import time


class ProbeHangError(RuntimeError):
    """The child exited 3 (failure_class="probe_hang"): the chip tunnel
    never answered — environment flakiness, not a code regression. The
    search loop catches this, journals a window_abort, and exits 3 so
    the operator re-lands the window; completed trials stay settled."""


class TrialRunError(RuntimeError):
    """The child failed for a non-hang reason (exit 1, missing result
    file, unparsable payload). Caught per-trial by the search loop: the
    trial is journaled ``failed`` and the search continues."""


@dataclasses.dataclass
class TrialResult:
    exit_code: int
    payload: dict | None        # the bench's ONE JSON line (BENCH_OUT)
    summary: dict | None = None  # dtf-run-summary/1, when the trial has one
    duration_s: float = 0.0


class SubprocessRunner:
    def __init__(self, cwd: str, *, bench_wait_min: float = 0.0,
                 timeout_s: float | None = None):
        self.cwd = cwd
        self.bench_wait_min = bench_wait_min
        self.timeout_s = timeout_s

    def run(self, trial_id: str, argv: list[str],
            env: dict[str, str]) -> TrialResult:
        merged = dict(os.environ)
        merged.update(env)
        if self.bench_wait_min and "BENCH_WAIT" not in env:
            # Forward the queue's retry budget (minutes) to the child.
            merged["BENCH_WAIT"] = str(self.bench_wait_min)
        with tempfile.TemporaryDirectory(prefix="autotune-") as tmp:
            out_path = os.path.join(tmp, "bench_out.json")
            merged.setdefault("BENCH_OUT", out_path)
            start = time.monotonic()
            try:
                proc = subprocess.run(
                    argv, cwd=self.cwd, env=merged,
                    timeout=self.timeout_s, stdout=subprocess.PIPE,
                    stderr=sys.stderr.fileno() if hasattr(sys.stderr, "fileno")
                    else None, text=True)
            except subprocess.TimeoutExpired as e:
                raise TrialRunError(
                    f"{trial_id}: timed out after {self.timeout_s}s") from e
            except OSError as e:
                raise TrialRunError(f"{trial_id}: launch failed: {e}") from e
            duration = time.monotonic() - start
            payload = self._read_payload(merged["BENCH_OUT"], proc.stdout)
            if proc.returncode == 3:
                raise ProbeHangError(
                    f"{trial_id}: backend probe HANG (exit 3) — aborting "
                    f"the window, journal stays resumable")
            if proc.returncode != 0:
                raise TrialRunError(
                    f"{trial_id}: exit {proc.returncode} "
                    f"(payload: {payload})")
            return TrialResult(exit_code=proc.returncode, payload=payload,
                               duration_s=duration)

    @staticmethod
    def _read_payload(out_path: str, stdout: str | None) -> dict | None:
        """BENCH_OUT file first; last JSON-parsable stdout line as the
        fallback for children that predate the BENCH_OUT contract
        (scripts/verify_fused_bwd.py et al.)."""
        try:
            with open(out_path) as fh:
                return json.load(fh)
        except (OSError, ValueError):
            pass
        for line in reversed((stdout or "").splitlines()):
            line = line.strip()
            if line.startswith("{"):
                try:
                    return json.loads(line)
                except ValueError:
                    continue
        return None


class FakeRunner:
    """Deterministic runner for the CPU smoke drill. ``spec`` maps trial
    id (or "*" default) to {"exit_code", "payload", "summary",
    "sleep_s"}; exit 3 raises ProbeHangError and nonzero raises
    TrialRunError, mirroring the subprocess taxonomy exactly so the
    search loop under test is the production one."""

    def __init__(self, spec: dict):
        self.spec = spec
        self.calls: list[str] = []

    @classmethod
    def from_file(cls, path: str) -> "FakeRunner":
        with open(path) as fh:
            return cls(json.load(fh))

    def run(self, trial_id: str, argv: list[str],
            env: dict[str, str]) -> TrialResult:
        self.calls.append(trial_id)
        rec = self.spec.get(trial_id) or self.spec.get("*") or {}
        sleep_s = float(rec.get("sleep_s") or 0.0)
        if sleep_s:
            time.sleep(sleep_s)
        rc = int(rec.get("exit_code") or 0)
        if rc == 3:
            raise ProbeHangError(f"{trial_id}: fake probe hang (exit 3)")
        if rc != 0:
            raise TrialRunError(f"{trial_id}: fake exit {rc}")
        return TrialResult(exit_code=0, payload=rec.get("payload"),
                           summary=rec.get("summary"), duration_s=sleep_s)
