"""Trial scoring: goodput-weighted throughput from dtf-run-summary/1.

Raw img/s is the wrong objective — PR 12's goodput ledger exists because
a config can win the compiled step and lose the run to infeed stall or
checkpoint blocking. A trial's score is therefore

    score = headline value (img|examples/sec/chip) x goodput_frac

with goodput_frac taken from the run summary's goodput ledger
(``scripts/analyze_trace.py --json`` → ``goodput_ledger.goodput_frac``).
A trial that produced no events stream scores at goodput 1.0 — the bench
is a synthetic-infeed closed loop, so its ledger is flat by construction
and penalizing its absence would just bias the search toward trials that
happened to write telemetry.
"""

from __future__ import annotations

RUN_SUMMARY_SCHEMA = "dtf-run-summary/1"


def goodput_frac(summary: dict | None) -> float:
    """goodput_frac from a dtf-run-summary/1 object, clamped to [0, 1];
    1.0 when no summary/ledger exists (see module docstring)."""
    ledger = (summary or {}).get("goodput_ledger") or {}
    frac = ledger.get("goodput_frac")
    if frac is None:
        return 1.0
    try:
        return min(1.0, max(0.0, float(frac)))
    except (TypeError, ValueError):
        return 1.0


def score_trial(payload: dict | None, summary: dict | None = None) -> dict:
    """Score one trial from its bench JSON line (+ optional run summary).

    Returns {"score", "value", "goodput_frac", "unit"}; score 0.0 when
    the bench produced no value (failure lines carry value 0.0 already,
    so a failed trial can never outrank a measured one).
    """
    payload = payload or {}
    try:
        value = float(payload.get("value") or 0.0)
    except (TypeError, ValueError):
        value = 0.0
    frac = goodput_frac(summary)
    return {
        "score": round(value * frac, 4),
        "value": value,
        "goodput_frac": frac,
        "unit": payload.get("unit"),
    }
