"""The search loop: enumerate → prune → run → score → journal → pin.

One loop serves both modes. Space mode turns a SearchSpace into bench
trials, prunes candidates the roofline model predicts more than
``prune_margin`` worse than the incumbent on the binding resource
(tools/autotune/model), runs the survivors through the runner, scores
them goodput-weighted (tools/autotune/scoring), and pins the winner in
configs/leaderboard.json + configs/best_<workload>.yaml. Plan mode runs
a compiled PlannedTrial list (tools/autotune/plan) through the same
journal/runner machinery — no pruning, the queue arms are all wanted.

Window-vs-search taxonomy: ProbeHangError (the runner's exit-3 class)
aborts the WINDOW — a ``window_abort`` journal record is written, the
loop stops, and the search resumes from the journal next window.
TrialRunError fails only its trial. Every decision (ran / pruned /
failed / aborted) is journaled (dtf-autotune-journal/1) and emitted as
KIND_AUTOTUNE_TRIAL telemetry when a writer is attached.
"""

from __future__ import annotations

from tools.autotune import model as traffic_model
from tools.autotune import scoring
from tools.autotune.journal import TrialJournal
from tools.autotune.runner import ProbeHangError, TrialRunError


def trial_id_for(overrides: dict) -> str:
    """Stable trial id for a candidate = its config digest, so the
    journal, the leaderboard and the telemetry all key the same way."""
    from tools.autotune.leaderboard import config_digest

    return config_digest(overrides)


class SearchResult(dict):
    """Plain dict subclass so callers can json.dump it directly."""


def _emit(writer, **payload) -> None:
    if writer is not None:
        from distributed_tensorflow_framework_tpu.core import telemetry

        writer.emit(telemetry.KIND_AUTOTUNE_TRIAL, **payload)


def run_space_search(space, profile, runner, journal: TrialJournal, *,
                     prune_margin: float = 0.05, max_trials: int = 0,
                     writer=None, log=print) -> SearchResult:
    """Search ``space`` for ``space.workload``; returns the tally dict
    {"workload", "ran", "pruned", "resumed", "failed", "aborted",
    "best": {trial, overrides, score...}|None}."""
    baseline = space.baseline()
    settled = journal.settled()
    best: dict | None = None
    # Resume: re-adopt the best settled score so a resumed window can't
    # crown a worse winner than the killed one already measured.
    for tid, rec in settled.items():
        if rec.get("status") == "done" and rec.get("score") is not None:
            if best is None or rec["score"] > best["score"]:
                best = {"trial": tid, "overrides": rec.get("overrides"),
                        "score": rec["score"], "value": rec.get("value"),
                        "goodput_frac": rec.get("goodput_frac"),
                        "unit": rec.get("unit"),
                        "payload": rec.get("payload")}
    tally = {"workload": space.workload, "ran": 0, "pruned": 0,
             "resumed": 0, "failed": 0, "aborted": False}
    for overrides in space.enumerate():
        if max_trials and tally["ran"] >= max_trials:
            log(f"autotune: max_trials={max_trials} reached — stopping")
            break
        tid = trial_id_for(overrides)
        if tid in settled:
            tally["resumed"] += 1
            log(f"autotune: {tid} already "
                f"{settled[tid].get('status')} (journal) — not re-running")
            continue
        skip, reason, detail = traffic_model.prune_decision(
            profile, overrides, baseline, prune_margin)
        if skip:
            tally["pruned"] += 1
            log(f"autotune: PRUNE {tid} {overrides}: {reason}")
            journal.record(tid, "skipped", overrides=overrides,
                           reason=reason, prediction=detail)
            _emit(writer, trial=tid, status="skipped", reason=reason,
                  overrides=overrides, prediction=detail)
            continue
        log(f"autotune: RUN {tid} {overrides}: {reason}")
        journal.record(tid, "started", overrides=overrides,
                       prediction=detail)
        _emit(writer, trial=tid, status="started", overrides=overrides)
        try:
            result = runner.run(tid, ["python", "bench.py"],
                                space.trial_env(overrides))
        except ProbeHangError as e:
            tally["aborted"] = True
            journal.record(tid, "window_abort", overrides=overrides,
                           error=str(e))
            _emit(writer, trial=tid, status="window_abort", error=str(e))
            log(f"autotune: WINDOW ABORT at {tid}: {e}")
            break
        except TrialRunError as e:
            tally["failed"] += 1
            journal.record(tid, "failed", overrides=overrides,
                           error=str(e))
            _emit(writer, trial=tid, status="failed", error=str(e))
            log(f"autotune: FAILED {tid}: {e}")
            continue
        tally["ran"] += 1
        scored = scoring.score_trial(result.payload, result.summary)
        journal.record(tid, "done", overrides=overrides,
                       payload=result.payload,
                       duration_s=round(result.duration_s, 3), **scored)
        _emit(writer, trial=tid, status="done", overrides=overrides,
              **scored)
        log(f"autotune: DONE {tid}: score {scored['score']} "
            f"({scored['value']} x goodput {scored['goodput_frac']})")
        if best is None or scored["score"] > best["score"]:
            best = {"trial": tid, "overrides": overrides,
                    "payload": result.payload, **scored}
    out = SearchResult(tally)
    out["best"] = best
    return out


def pin_winner(result: SearchResult, *, leaderboard_path: str,
               best_yaml_path: str, regression_margin: float = 0.05,
               provenance: dict | None = None, log=print) -> dict | None:
    """Write the leaderboard entry + best_<workload>.yaml for the
    search's winner (no-op when nothing scored)."""
    from tools.autotune import leaderboard as board

    best = result.get("best")
    if not best or not best.get("overrides"):
        log("autotune: no winner to pin (nothing scored)")
        return None
    payload = best.get("payload") or {}
    entry = board.pin_entry(
        leaderboard_path, result["workload"],
        config=best["overrides"], score=best["score"],
        unit=best.get("unit") or payload.get("unit") or "",
        bound=payload.get("bound"), chip=payload.get("chip"),
        provenance=provenance or {},
        regression_margin=regression_margin)
    board.write_best_yaml(
        best_yaml_path, result["workload"], best["overrides"],
        score=best["score"], digest=entry["config_digest"])
    log(f"autotune: pinned {result['workload']} incumbent "
        f"{entry['config_digest']} score {entry['score']} "
        f"→ {leaderboard_path}")
    return entry


def run_plan(trials, runner, journal: TrialJournal, *, writer=None,
             log=print) -> SearchResult:
    """Execute a compiled PlannedTrial list (plan mode). Preflight
    failures and probe hangs abort the window (the §0/§0b contract);
    gated trials are skipped when their gate didn't succeed; everything
    is journaled under the trial's §section/label id for resume."""
    settled = journal.settled()
    tally = {"workload": "chip_window", "ran": 0, "pruned": 0,
             "resumed": 0, "failed": 0, "aborted": False,
             "preflight_failed": False}
    succeeded: set[str] = {
        rec.get("label") or tid for tid, rec in settled.items()
        if rec.get("status") == "done"}
    for trial in trials:
        tid = f"s{trial.section}:{trial.label}"
        if tid in settled:
            tally["resumed"] += 1
            if settled[tid].get("status") == "done":
                succeeded.add(trial.label)
            log(f"autotune: {tid} already "
                f"{settled[tid].get('status')} (journal) — not re-running")
            continue
        if trial.gate and trial.gate not in succeeded:
            tally["pruned"] += 1
            reason = f"gate {trial.gate!r} did not succeed"
            journal.record(tid, "skipped", label=trial.label,
                           section=trial.section, reason=reason)
            _emit(writer, trial=tid, status="skipped", reason=reason)
            log(f"autotune: SKIP {tid}: {reason}")
            continue
        journal.record(tid, "started", label=trial.label,
                       section=trial.section)
        _emit(writer, trial=tid, status="started", section=trial.section)
        try:
            result = runner.run(tid, list(trial.argv), trial.env_dict())
        except ProbeHangError as e:
            tally["aborted"] = True
            journal.record(tid, "window_abort", label=trial.label,
                           error=str(e))
            _emit(writer, trial=tid, status="window_abort", error=str(e))
            log(f"autotune: WINDOW ABORT at {tid}: {e}")
            break
        except TrialRunError as e:
            tally["failed"] += 1
            journal.record(tid, "failed", label=trial.label, error=str(e))
            _emit(writer, trial=tid, status="failed", error=str(e))
            log(f"autotune: FAILED {tid}: {e}")
            if trial.kind == "preflight":
                # §0/§0b: a failed preflight refuses the window.
                tally["preflight_failed"] = True
                log(f"autotune: preflight {tid} failed — refusing to "
                    f"spend the window")
                break
            continue
        tally["ran"] += 1
        succeeded.add(trial.label)
        scored = scoring.score_trial(result.payload, result.summary)
        journal.record(tid, "done", label=trial.label,
                       section=trial.section, payload=result.payload,
                       duration_s=round(result.duration_s, 3), **scored)
        _emit(writer, trial=tid, status="done", section=trial.section,
              **scored)
        log(f"autotune: DONE {tid} (score {scored['score']})")
    return SearchResult(tally)
