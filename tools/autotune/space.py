"""Typed SearchSpace over the REAL config dataclasses.

A knob is a dotted ``section.field`` path into ExperimentConfig
(core/config.py) plus the candidate values to try; the space is their
cartesian product. Paths are validated against the actual
``@config_dataclass`` definitions at construction — a tuner that
enumerates knobs the config system doesn't have would spend chip time
benchmarking typos. Knobs optionally carry the BENCH_* env var that
feeds the setting to bench.py's driver contract, so a trial can be
launched as a supervised subprocess without editing config files.
"""

from __future__ import annotations

import dataclasses
import itertools
import json


class SearchSpaceError(ValueError):
    """An invalid knob spec: unknown config section/field, empty value
    list, or an unparsable space file. Raised while BUILDING the space —
    before any chip time is spent — and surfaced by scripts/autotune.py
    as a config error (exit 1)."""


def _config_sections() -> dict[str, list[str]]:
    """{section: [field, ...]} from the real ExperimentConfig tree."""
    from distributed_tensorflow_framework_tpu.core.config import (
        ExperimentConfig,
    )

    sections: dict[str, list[str]] = {}
    for sec in dataclasses.fields(ExperimentConfig):
        factory = sec.default_factory if sec.default_factory is not dataclasses.MISSING else None
        if factory is not None and dataclasses.is_dataclass(factory):
            sections[sec.name] = [f.name for f in dataclasses.fields(factory)]
    # Optional sections (eval_data: DataConfig | None) share DataConfig's
    # fields with their non-optional sibling; scalar fields (name) are not
    # tunable sections and are deliberately absent.
    return sections


@dataclasses.dataclass(frozen=True)
class Knob:
    """One searchable dimension: ``path`` = dotted section.field into
    ExperimentConfig, ``values`` = settings to try (first value = the
    baseline the incumbent is assumed to run), ``env`` = the BENCH_* env
    var that carries the setting to a bench.py subprocess ("" = config
    override only)."""

    path: str
    values: tuple
    env: str = ""


class SearchSpace:
    def __init__(self, workload: str, knobs: list[Knob]):
        self.workload = workload
        self.knobs = list(knobs)
        self.validate()

    def validate(self) -> None:
        sections = _config_sections()
        for knob in self.knobs:
            section, _, field = knob.path.partition(".")
            if section not in sections:
                raise SearchSpaceError(
                    f"knob {knob.path!r}: {section!r} is not a config "
                    f"section (have: {sorted(sections)})")
            if field not in sections[section]:
                raise SearchSpaceError(
                    f"knob {knob.path!r}: {section!r} has no field "
                    f"{field!r} (have: {sorted(sections[section])})")
            if not knob.values:
                raise SearchSpaceError(f"knob {knob.path!r}: empty values")

    def baseline(self) -> dict[str, object]:
        """The incumbent's assumed settings: each knob's first value."""
        return {k.path: k.values[0] for k in self.knobs}

    def enumerate(self) -> list[dict[str, object]]:
        """All candidate override dicts, baseline first (itertools
        product order with each knob's values in spec order)."""
        paths = [k.path for k in self.knobs]
        combos = itertools.product(*(k.values for k in self.knobs))
        return [dict(zip(paths, combo)) for combo in combos]

    def trial_env(self, overrides: dict[str, object]) -> dict[str, str]:
        """BENCH_* env assignments for one candidate (env-mapped knobs
        only; empty-string values still exported — bench treats "" as
        unset, which IS the baseline arm for mode-owning envs)."""
        env = {}
        for knob in self.knobs:
            if knob.env:
                env[knob.env] = str(overrides[knob.path])
        return env

    @classmethod
    def from_spec(cls, spec: dict) -> "SearchSpace":
        """Build from a parsed JSON spec: {"workload": ..., "knobs":
        [{"path": ..., "values": [...], "env": ...}, ...]}."""
        try:
            knobs = [Knob(path=k["path"], values=tuple(k["values"]),
                          env=k.get("env", ""))
                     for k in spec["knobs"]]
            return cls(str(spec["workload"]), knobs)
        except (KeyError, TypeError) as e:
            raise SearchSpaceError(f"malformed space spec: {e}") from e

    @classmethod
    def from_file(cls, path: str) -> "SearchSpace":
        try:
            with open(path) as fh:
                spec = json.load(fh)
        except (OSError, ValueError) as e:
            raise SearchSpaceError(f"space file {path}: {e}") from e
        return cls.from_spec(spec)
