"""graftcheck — framework-aware static analysis for this repo.

Three layers (docs/STATIC_ANALYSIS.md):

  * **ast** — stdlib ``ast`` passes over the package and tests: raw-collective
    ban, host-sync-in-step, config-knob coverage, telemetry-kind coverage,
    slow-marker audit, typed-error conventions, and the concurrency
    contracts (thread-lifecycle, lock-discipline).
  * **jaxpr** — trace audits that jit-trace the real train step on the
    8-device CPU mesh and walk the ClosedJaxpr: donation elision, f32
    upcasts of bf16/int8-designated tensors, and the collective-op census
    cross-checked against the ``CollectiveTally`` the same trace records.
  * **hlo** — compiled-artifact audits that ``lower().compile()`` the real
    train step and serve forward and read the optimized module: GSPMD
    reshard census, input_output_alias donation survival, and
    ``memory_analysis()`` bytes gated against ``configs/hlo_budgets.json``.

Entry point: ``scripts/graftcheck.py`` (human table + ``dtf-lint-report/1``
JSON, per-finding suppression file, distinct exit codes). The suite is
self-enforcing: ``tests/test_graftcheck.py::test_self_audit_repo_is_clean``
runs it over the repo in tier-1 and asserts zero findings.

Importing this package registers every pass; jax itself is imported lazily
inside the jaxpr-layer pass bodies so AST-only runs (``--changed``
pre-commit mode) stay dependency-light and fast.
"""

from tools.graftcheck.findings import (  # noqa: F401
    Finding,
    REPORT_SCHEMA,
    build_report,
    load_suppressions,
    validate_report,
)
from tools.graftcheck.registry import PASSES, get_pass, passes_for_layer  # noqa: F401

# Importing the pass modules registers them.
from tools.graftcheck import ast_passes as _ast_passes  # noqa: E402,F401
from tools.graftcheck import concurrency_passes as _concurrency_passes  # noqa: E402,F401
from tools.graftcheck import jaxpr_passes as _jaxpr_passes  # noqa: E402,F401
from tools.graftcheck import hlo_passes as _hlo_passes  # noqa: E402,F401
