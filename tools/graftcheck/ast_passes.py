"""Layer-1 (source/AST) passes.

Each pass is registered with the shared registry and reads the repo
exclusively through a :class:`~tools.graftcheck.context.RepoContext`, so
the identical logic runs against the real repo (self-audit) and against the
fixture mini-repos under ``tests/graftcheck_fixtures/``. File-level helpers
(``scan_raw_collectives`` etc.) are public so the fixture tests exercise
each rule on a single file without constructing a whole context.
"""

from __future__ import annotations

import ast
import pathlib
import re

from tools.graftcheck.context import DEFAULT_PACKAGE, RepoContext
from tools.graftcheck.findings import Finding
from tools.graftcheck.registry import LAYER_AST, register

# ------------------------------------------------------------------------
# raw-collective: lax.psum & friends outside parallel/ bypass the
# CollectiveTally byte accounting (PR 7's wire-byte honesty contract).
# ------------------------------------------------------------------------

BANNED_COLLECTIVES = frozenset({
    "psum", "pmean", "pmax", "pmin", "all_gather", "ppermute",
    "psum_scatter", "all_to_all", "pbroadcast",
})
COLLECTIVE_EXEMPT_SUBDIR = "parallel"


def _is_lax(node: ast.expr) -> bool:
    """``lax`` or ``jax.lax`` (the two in-repo spellings)."""
    if isinstance(node, ast.Name):
        return node.id == "lax"
    return (isinstance(node, ast.Attribute) and node.attr == "lax"
            and isinstance(node.value, ast.Name) and node.value.id == "jax")


def scan_raw_collectives(rel: str, tree: ast.Module) -> list[Finding]:
    out = []
    for node in ast.walk(tree):
        if (isinstance(node, ast.Attribute)
                and node.attr in BANNED_COLLECTIVES and _is_lax(node.value)):
            out.append(Finding(
                "raw-collective", f"{rel}:{node.lineno}",
                f"raw lax.{node.attr} bypasses the CollectiveTally byte "
                f"accounting — use the parallel/collectives.py wrapper (or "
                f"add a justified suppression)"))
        if (isinstance(node, ast.ImportFrom) and node.module == "jax.lax"
                and any(a.name in BANNED_COLLECTIVES for a in node.names)):
            names = [a.name for a in node.names if a.name in BANNED_COLLECTIVES]
            out.append(Finding(
                "raw-collective", f"{rel}:{node.lineno}",
                f"importing {names} from jax.lax invites untallied "
                f"collectives — use parallel/collectives.py wrappers"))
    return out


@register(
    "raw-collective", LAYER_AST,
    "ban raw lax collectives outside parallel/ (they bypass the wire-byte "
    "tally the int8-compression numbers are benchmarked on)")
def raw_collective_pass(ctx: RepoContext) -> list[Finding]:
    findings = []
    exempt = ctx.pkg_dir / COLLECTIVE_EXEMPT_SUBDIR
    for path in ctx.pkg_files() + ctx.test_files() + ctx.script_files():
        if path.is_relative_to(exempt) or not ctx.selected(path):
            continue
        findings.extend(scan_raw_collectives(ctx.rel(path), ctx.tree(path)))
    return findings


# ------------------------------------------------------------------------
# host-sync-in-step: host synchronization reachable from the train-step
# builders stalls the device queue and pollutes the goodput ledger's
# step_compute bucket (PR 10) with host time.
# ------------------------------------------------------------------------

HOST_SYNC_FILES = ("train/step.py", "train/losses.py")
_HOST_SYNC_ATTRS = frozenset({"item", "device_get", "block_until_ready"})
_NUMPY_NAMES = frozenset({"np", "numpy"})


def scan_host_sync(rel: str, tree: ast.Module) -> list[Finding]:
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Attribute) and node.attr in _HOST_SYNC_ATTRS:
            out.append(Finding(
                "host-sync-in-step", f"{rel}:{node.lineno}",
                f".{node.attr} in step-builder code forces a device→host "
                f"sync inside the hot loop — keep metrics on device and "
                f"fetch them from the train loop"))
        elif (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id in _NUMPY_NAMES):
            out.append(Finding(
                "host-sync-in-step", f"{rel}:{node.lineno}",
                f"numpy ({node.value.id}.{node.attr}) in step-builder code "
                f"materializes on host — use jnp so the op stays in the "
                f"compiled step"))
        elif (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
                and node.func.id in ("float", "int")
                and node.args
                and not isinstance(node.args[0], ast.Constant)):
            out.append(Finding(
                "host-sync-in-step", f"{rel}:{node.lineno}",
                f"{node.func.id}() on a traced value blocks on the device "
                f"queue (implicit device_get) — keep it a jnp scalar"))
    return out


@register(
    "host-sync-in-step", LAYER_AST,
    "ban .item()/float()/numpy/device_get in the train-step builder "
    "modules (host syncs there pollute the goodput step_compute bucket)")
def host_sync_pass(ctx: RepoContext) -> list[Finding]:
    findings = []
    for rel_name in HOST_SYNC_FILES:
        path = ctx.pkg_dir / rel_name
        if not path.exists() or not ctx.selected(path):
            continue
        findings.extend(scan_host_sync(ctx.rel(path), ctx.tree(path)))
    return findings


# ------------------------------------------------------------------------
# config-knob-coverage: every knob the config system validates must be
# consumed somewhere in the package AND documented, or it is dead weight
# that silently diverges from behavior.
# ------------------------------------------------------------------------

def _config_fields(tree: ast.Module) -> dict[str, list[str]]:
    """{class_name: [field, ...]} for @config_dataclass classes."""
    sections: dict[str, list[str]] = {}
    for node in tree.body:
        if not isinstance(node, ast.ClassDef):
            continue
        if not any(isinstance(d, ast.Name) and d.id == "config_dataclass"
                   for d in node.decorator_list):
            continue
        fields = [s.target.id for s in node.body
                  if isinstance(s, ast.AnnAssign)
                  and isinstance(s.target, ast.Name)
                  and not s.target.id.startswith("_")]
        sections[node.name] = fields
    return sections


_WORD = re.compile(r"[A-Za-z_][A-Za-z0-9_]*")


def _usage_corpus(ctx: RepoContext, config_path: pathlib.Path) -> set[str]:
    """Identifiers 'read' by the package: attribute accesses plus words in
    string constants (mesh axes and telemetry field names travel as
    strings). core/config.py itself is excluded — validation is not
    consumption."""
    seen: set[str] = set()
    for path in ctx.pkg_files() + ctx.script_files():
        if path.resolve() == config_path.resolve():
            continue
        for node in ast.walk(ctx.tree(path)):
            if isinstance(node, ast.Attribute):
                seen.add(node.attr)
            elif isinstance(node, ast.Constant) and isinstance(node.value, str):
                seen.update(_WORD.findall(node.value))
    return seen


@register(
    "config-knob-coverage", LAYER_AST,
    "every validated config knob must be read in the package and mentioned "
    "in docs/ (undocumented or unread knobs silently diverge from behavior)",
    anchors=("*/core/config.py", "docs/*.md", "README.md",
             DEFAULT_PACKAGE + "/*", "scripts/*.py"))
def config_coverage_pass(ctx: RepoContext) -> list[Finding]:
    config_path = ctx.pkg_dir / "core" / "config.py"
    rel = ctx.rel(config_path) if config_path.exists() else "core/config.py"
    if not config_path.exists():
        return [Finding("config-knob-coverage", rel,
                        "core/config.py not found", severity="internal-error")]
    sections = _config_fields(ctx.tree(config_path))
    if not sections:
        return [Finding(
            "config-knob-coverage", rel,
            "no @config_dataclass classes found — extraction is broken "
            "(vacuous pass)", severity="internal-error")]
    used = _usage_corpus(ctx, config_path)
    docs = "\n".join(ctx.source(p) for p in ctx.doc_files())
    findings = []
    for cls, fields in sections.items():
        for f in fields:
            if f not in used:
                findings.append(Finding(
                    "config-knob-coverage", f"{rel}:{cls}.{f}",
                    f"knob {cls}.{f} is never read outside core/config.py — "
                    f"dead config surface (wire it up or delete it)"))
            if not re.search(r"\b" + re.escape(f) + r"\b", docs):
                findings.append(Finding(
                    "config-knob-coverage", f"{rel}:{cls}.{f}",
                    f"knob {cls}.{f} appears nowhere in docs/*.md or "
                    f"README.md — document it (docs/CONFIG.md is the knob "
                    f"reference)"))
    return findings


# ------------------------------------------------------------------------
# telemetry-kind-coverage: every KIND_* event and every CollectiveTally
# grand-total field must be rolled up by the summary surface and pinned by
# at least one test (promoted from tests/test_marker_audit.py).
# ------------------------------------------------------------------------

def _module_const_assigns(tree: ast.Module, prefix: str) -> dict[str, object]:
    out: dict[str, object] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id.startswith(prefix):
                    try:
                        out[t.id] = ast.literal_eval(node.value)
                    except ValueError:
                        out[t.id] = None
    return out


def _function_source(tree: ast.Module, source: str, name: str) -> str | None:
    for node in tree.body:
        if isinstance(node, ast.FunctionDef) and node.name == name:
            return ast.get_source_segment(source, node) or ""
    return None


@register(
    "telemetry-kind-coverage", LAYER_AST,
    "every KIND_* telemetry constant and CollectiveTally total field must "
    "be summarized by the rollup surface and referenced by a test",
    anchors=("*/core/telemetry.py", "*/parallel/collectives.py",
             "tests/test_*.py"))
def telemetry_coverage_pass(ctx: RepoContext) -> list[Finding]:
    telem = ctx.pkg_dir / "core" / "telemetry.py"
    coll = ctx.pkg_dir / "parallel" / "collectives.py"
    findings: list[Finding] = []
    if not telem.exists():
        return [Finding("telemetry-kind-coverage", "core/telemetry.py",
                        "telemetry module not found",
                        severity="internal-error")]
    rel = ctx.rel(telem)
    source = ctx.source(telem)
    tree = ctx.tree(telem)
    kinds = _module_const_assigns(tree, "KIND_")
    is_real_repo = ctx.package == DEFAULT_PACKAGE
    if is_real_repo and len(kinds) < 20:
        findings.append(Finding(
            "telemetry-kind-coverage", rel,
            f"KIND_* extraction saw only {len(kinds)} constants (expected "
            f">= 20) — the audit is degraded, not the repo clean",
            severity="internal-error"))
    by_value: dict[object, list[str]] = {}
    for name, value in kinds.items():
        by_value.setdefault(value, []).append(name)
    for value, names in by_value.items():
        if len(names) > 1:
            findings.append(Finding(
                "telemetry-kind-coverage", f"{rel}:{'/'.join(sorted(names))}",
                f"telemetry kinds {sorted(names)} share the string value "
                f"{value!r} — rollups cannot distinguish them"))
    rollup_parts = [
        _function_source(tree, source, "summarize_events"),
        _function_source(tree, source, "format_run_summary"),
    ]
    if any(p is None for p in rollup_parts):
        findings.append(Finding(
            "telemetry-kind-coverage", rel,
            "summarize_events/format_run_summary not found — the rollup "
            "surface moved; update the pass", severity="internal-error"))
        return findings
    summarize_src, format_src = (p or "" for p in rollup_parts)
    rollup_src = summarize_src + format_src
    corpus = "".join(ctx.source(p) for p in ctx.test_files())
    for name in kinds:
        # Per-part check: an event accumulated by summarize_events but
        # never surfaced by format_run_summary (or vice versa) is still
        # invisible in post-mortems — each part must name the kind (a
        # per-kind rollup comment counts; the convention makes the
        # printed line greppable back to its constant).
        missing = [fn for fn, src in (("summarize_events", summarize_src),
                                      ("format_run_summary", format_src))
                   if name not in src]
        if missing:
            findings.append(Finding(
                "telemetry-kind-coverage", f"{rel}:{name}",
                f"{name} has no rollup in {' or '.join(missing)} "
                f"— the event is invisible in exactly the post-mortems it "
                f"was added for"))
        if name not in corpus:
            findings.append(Finding(
                "telemetry-kind-coverage", f"{rel}:{name}",
                f"{name} is referenced by no test — it can silently rot"))
    if coll.exists():
        crel = ctx.rel(coll)
        fields = _module_const_assigns(
            ctx.tree(coll), "TALLY_TOTAL_FIELDS").get("TALLY_TOTAL_FIELDS")
        if not fields:
            if is_real_repo:
                findings.append(Finding(
                    "telemetry-kind-coverage", crel,
                    "TALLY_TOTAL_FIELDS not found in parallel/collectives.py",
                    severity="internal-error"))
        else:
            if is_real_repo and not {"total_bytes",
                                     "total_logical_bytes"} <= set(fields):
                findings.append(Finding(
                    "telemetry-kind-coverage", crel,
                    f"TALLY_TOTAL_FIELDS lost its core fields: {fields}",
                    severity="internal-error"))
            for f in fields:
                if f not in rollup_src:
                    findings.append(Finding(
                        "telemetry-kind-coverage", f"{crel}:{f}",
                        f"CollectiveTally total field {f!r} has no telemetry "
                        f"rollup — an unprinted total silently rots"))
                if f not in corpus:
                    findings.append(Finding(
                        "telemetry-kind-coverage", f"{crel}:{f}",
                        f"CollectiveTally total field {f!r} is referenced by "
                        f"no test"))
    return findings


# ------------------------------------------------------------------------
# slow-marker: subprocess training drills (the DRIVER template family)
# must be tier-2 — tier-1 is the under-15-minute per-PR gate.
# ------------------------------------------------------------------------

_DRIVER_NAME = "DRIVER"


def _is_driver_name(name: str) -> bool:
    return name == _DRIVER_NAME or name.endswith("_" + _DRIVER_NAME)


def _decorator_marks(fn: ast.FunctionDef) -> set[str]:
    marks: set[str] = set()
    for dec in fn.decorator_list:
        node = dec.func if isinstance(dec, ast.Call) else dec
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Attribute)
                and node.value.attr == "mark"):
            marks.add(node.attr)
    return marks


def module_defines_driver(tree: ast.Module) -> bool:
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and _is_driver_name(t.id):
                    return True
        if isinstance(node, ast.ImportFrom):
            if any(_is_driver_name(a.name) for a in node.names):
                return True
    return False


def function_uses_driver(fn: ast.FunctionDef) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and _is_driver_name(node.id):
            return True
        if isinstance(node, ast.ImportFrom) and \
                any(_is_driver_name(a.name) for a in node.names):
            return True
    return False


def scan_slow_markers(rel: str, tree: ast.Module) -> list[Finding]:
    out = []
    module_wide = module_defines_driver(tree)
    for node in tree.body:
        if not (isinstance(node, ast.FunctionDef)
                and node.name.startswith("test_")):
            continue
        if not (module_wide or function_uses_driver(node)):
            continue
        if "slow" not in _decorator_marks(node):
            out.append(Finding(
                "slow-marker", f"{rel}:{node.lineno}",
                f"{node.name} launches real training children (DRIVER "
                f"template) but lacks @pytest.mark.slow — subprocess "
                f"drills must stay out of tier-1"))
    return out


@register(
    "slow-marker", LAYER_AST,
    "subprocess training drills (DRIVER template) must carry "
    "@pytest.mark.slow so they stay out of the tier-1 gate",
    anchors=("tests/test_*.py",))
def slow_marker_pass(ctx: RepoContext) -> list[Finding]:
    findings = []
    recognized_known_drill = False
    sentinel = ctx.tests_dir / "test_fault_tolerance.py"
    for path in ctx.test_files():
        tree = ctx.tree(path)
        if path == sentinel and module_defines_driver(tree):
            recognized_known_drill = True
        findings.extend(scan_slow_markers(ctx.rel(path), tree))
    if sentinel.exists() and not recognized_known_drill:
        findings.append(Finding(
            "slow-marker", ctx.rel(sentinel),
            "audit no longer recognizes the known DRIVER drill module — "
            "the pass is matching nothing (vacuous)",
            severity="internal-error"))
    return findings


# ------------------------------------------------------------------------
# typed-errors: failures must be typed (the supervisor maps exception
# types to exit codes — rc 84 elastic refit rides MeshSizeError) and
# documented; anonymous Exception raises and bare excepts defeat that.
# ------------------------------------------------------------------------

_EXC_BASE_SUFFIXES = ("Error", "Exception")


def _base_name(node: ast.expr) -> str | None:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


def scan_typed_errors(rel: str, tree: ast.Module) -> list[Finding]:
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Raise) and node.exc is not None:
            target = node.exc.func if isinstance(node.exc, ast.Call) else node.exc
            name = _base_name(target)
            if name in ("Exception", "BaseException"):
                out.append(Finding(
                    "typed-errors", f"{rel}:{node.lineno}",
                    f"raise {name} is untyped — callers (and the "
                    f"supervisor's rc mapping) cannot dispatch on it; raise "
                    f"a *Error subclass"))
        elif isinstance(node, ast.ExceptHandler) and node.type is None:
            out.append(Finding(
                "typed-errors", f"{rel}:{node.lineno}",
                "bare 'except:' swallows SystemExit/KeyboardInterrupt — "
                "catch Exception (or narrower) explicitly"))
        elif isinstance(node, ast.ClassDef):
            base_names = [_base_name(b) for b in node.bases]
            if any(n and n.endswith(_EXC_BASE_SUFFIXES) for n in base_names):
                if not node.name.endswith("Error"):
                    out.append(Finding(
                        "typed-errors", f"{rel}:{node.lineno}",
                        f"exception class {node.name} must be named "
                        f"*Error (repo typed-error convention)"))
                if not ast.get_docstring(node):
                    out.append(Finding(
                        "typed-errors", f"{rel}:{node.lineno}",
                        f"exception class {node.name} needs a docstring "
                        f"saying when it fires and who catches it"))
    return out


@register(
    "typed-errors", LAYER_AST,
    "package failures must be typed *Error classes with docstrings; no "
    "anonymous 'raise Exception' or bare 'except:'")
def typed_errors_pass(ctx: RepoContext) -> list[Finding]:
    findings = []
    for path in ctx.pkg_files():
        if not ctx.selected(path):
            continue
        findings.extend(scan_typed_errors(ctx.rel(path), ctx.tree(path)))
    return findings
