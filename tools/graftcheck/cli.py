"""graftcheck CLI — run passes, apply suppressions, report, exit.

Exit codes (scripts consume these — scripts/chip_window_queue.sh gates the
chip window on 0):

  * ``0`` — clean (every finding suppressed or none at all)
  * ``1`` — unsuppressed findings
  * ``2`` — internal errors (a pass crashed or detected its own vacuity);
    never suppressible, because a broken audit must not read as a clean repo
  * ``3`` — usage error (bad flag, unknown pass)
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys

from tools.graftcheck import findings as fmod
from tools.graftcheck import registry
from tools.graftcheck.context import RepoContext, git_changed_files
from tools.graftcheck.findings import Finding

EXIT_CLEAN = 0
EXIT_FINDINGS = 1
EXIT_INTERNAL = 2
EXIT_USAGE = 3

DEFAULT_SUPPRESSIONS = pathlib.Path(__file__).with_name("suppressions.txt")


class _Parser(argparse.ArgumentParser):
    def error(self, message):  # argparse defaults to exit code 2
        self.exit(EXIT_USAGE, f"{self.prog}: error: {message}\n")


def build_parser() -> argparse.ArgumentParser:
    p = _Parser(
        prog="graftcheck",
        description="framework-aware static analysis: AST lints + jaxpr "
                    "trace audits (docs/STATIC_ANALYSIS.md)")
    p.add_argument("--root", default=".", help="repo root (default: cwd)")
    p.add_argument("--layer", choices=registry.LAYERS,
                   help="run only this layer's passes")
    p.add_argument("--pass", dest="passes", action="append", default=[],
                   metavar="PASS_ID", help="run only the named pass "
                   "(repeatable); overrides --layer")
    p.add_argument("--changed", action="store_true",
                   help="fast pre-commit mode: scan only files changed vs "
                   "HEAD; anchored repo-wide passes run only when an anchor "
                   "changed; jaxpr passes are skipped unless named with "
                   "--pass or --layer jaxpr")
    p.add_argument("--list-passes", action="store_true",
                   help="list registered passes and exit")
    p.add_argument("--json", metavar="FILE",
                   help="also write the dtf-lint-report/1 JSON here "
                   "('-' for stdout)")
    p.add_argument("--format", choices=("table", "json"), default="table",
                   help="stdout format (default: table)")
    p.add_argument("--suppressions", default=str(DEFAULT_SUPPRESSIONS),
                   help="suppression file (default: tools/graftcheck/"
                   "suppressions.txt)")
    return p


def select_passes(args, changed: set[str] | None) -> list[registry.PassInfo]:
    if args.passes:
        return [registry.get_pass(pid) for pid in args.passes]
    infos = list(registry.PASSES.values())
    if args.layer:
        infos = [p for p in infos if p.layer == args.layer]
    elif args.changed:
        # jaxpr probes cost seconds; the fast pre-commit loop is AST-only
        # unless the caller asks for the trace audits explicitly.
        infos = [p for p in infos if p.layer == registry.LAYER_AST]
    if changed is not None:
        infos = [p for p in infos if p.relevant_for_changed(changed)]
    return infos


def run_passes(ctx: RepoContext,
               infos: list[registry.PassInfo]) -> list[Finding]:
    findings: list[Finding] = []
    for info in infos:
        try:
            findings.extend(info.fn(ctx))
        except Exception as exc:  # a crashed audit must not read as clean
            findings.append(Finding(
                info.pass_id, "pass", f"pass crashed: {exc!r}",
                severity=fmod.SEVERITY_INTERNAL))
    return findings


def format_table(report: dict, infos: list[registry.PassInfo]) -> str:
    lines = []
    rows = [f for f in report["findings"] if not f["suppressed"]]
    sup = [f for f in report["findings"] if f["suppressed"]]
    if rows:
        w_pass = max(len(f["pass_id"]) for f in rows)
        w_where = max(len(f["where"]) for f in rows)
        for f in sorted(rows, key=lambda f: (f["pass_id"], f["where"])):
            tag = " [internal]" if f["severity"] == fmod.SEVERITY_INTERNAL else ""
            lines.append(f"{f['pass_id']:<{w_pass}}  {f['where']:<{w_where}}"
                         f"  {f['message']}{tag}")
        lines.append("")
    c = report["counts"]
    lines.append(
        f"graftcheck: {len(infos)} pass(es) run, {c['findings']} finding(s)"
        f" ({c['internal_errors']} internal), {c['suppressed']} suppressed")
    for f in sup:
        lines.append(f"  suppressed: {f['pass_id']} {f['where']} — "
                     f"{f['justification']}")
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)

    if args.list_passes:
        for info in sorted(registry.PASSES.values(),
                           key=lambda p: (p.layer, p.pass_id)):
            print(f"{info.pass_id:<26} [{info.layer}]  {info.description}")
        return EXIT_CLEAN

    root = pathlib.Path(args.root).resolve()
    changed = None
    if args.changed:
        try:
            changed = git_changed_files(root)
        except RuntimeError as exc:
            print(f"graftcheck: --changed needs git: {exc}", file=sys.stderr)
            return EXIT_USAGE

    try:
        infos = select_passes(args, changed)
    except KeyError as exc:
        print(f"graftcheck: {exc.args[0]}", file=sys.stderr)
        return EXIT_USAGE

    ctx = RepoContext(root, changed=changed)
    findings = run_passes(ctx, infos)
    sups, sup_findings = fmod.load_suppressions(args.suppressions)
    findings.extend(sup_findings)
    full_run = (changed is None and not args.passes and not args.layer)
    stale = fmod.apply_suppressions(
        findings, sups, suppression_file=pathlib.Path(args.suppressions).name,
        stale_check_ids=None if full_run else {i.pass_id for i in infos})
    if changed is None:  # --changed sees partial files; can't judge staleness
        findings.extend(stale)

    report = fmod.build_report(findings, [i.pass_id for i in infos], root)
    if args.format == "json":
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        print(format_table(report, infos))
    if args.json:
        payload = json.dumps(report, indent=2, sort_keys=True)
        if args.json == "-":
            if args.format != "json":
                print(payload)
        else:
            pathlib.Path(args.json).write_text(payload + "\n")

    if report["counts"]["internal_errors"]:
        return EXIT_INTERNAL
    if report["counts"]["findings"]:
        return EXIT_FINDINGS
    return EXIT_CLEAN
