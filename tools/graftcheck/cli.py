"""graftcheck CLI — run passes, apply suppressions, report, exit.

Exit codes (scripts consume these — scripts/chip_window_queue.sh gates the
chip window on 0):

  * ``0`` — clean (every finding suppressed or none at all)
  * ``1`` — unsuppressed findings
  * ``2`` — internal errors (a pass crashed or detected its own vacuity);
    never suppressible, because a broken audit must not read as a clean repo
  * ``3`` — usage error (bad flag, unknown pass)
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys

from tools.graftcheck import findings as fmod
from tools.graftcheck import registry
from tools.graftcheck.context import RepoContext, git_changed_files
from tools.graftcheck.findings import Finding

EXIT_CLEAN = 0
EXIT_FINDINGS = 1
EXIT_INTERNAL = 2
EXIT_USAGE = 3

DEFAULT_SUPPRESSIONS = pathlib.Path(__file__).with_name("suppressions.txt")


class _Parser(argparse.ArgumentParser):
    def error(self, message):  # argparse defaults to exit code 2
        self.exit(EXIT_USAGE, f"{self.prog}: error: {message}\n")


def build_parser() -> argparse.ArgumentParser:
    p = _Parser(
        prog="graftcheck",
        description="framework-aware static analysis: AST lints, jaxpr "
                    "trace audits, compiled-HLO audits "
                    "(docs/STATIC_ANALYSIS.md)")
    p.add_argument("--root", default=".", help="repo root (default: cwd)")
    p.add_argument("--layer", choices=registry.LAYERS,
                   help="run only this layer's passes")
    p.add_argument("--pass", dest="passes", action="append", default=[],
                   metavar="PASS_ID", help="run only the named pass "
                   "(repeatable); overrides --layer")
    p.add_argument("--changed", action="store_true",
                   help="fast pre-commit mode: scan only files changed vs "
                   "HEAD; anchored repo-wide passes run only when an anchor "
                   "changed; trace passes (jaxpr/hlo) are skipped with a "
                   "notice unless --trace, --pass, or --layer opts them in")
    p.add_argument("--trace", action="store_true",
                   help="with --changed: run the jaxpr/hlo trace passes "
                   "too (seconds of compile time) instead of skipping them")
    p.add_argument("--update-budgets", action="store_true",
                   help="fresh-compile every budgeted program and rewrite "
                   "configs/hlo_budgets.json (provenance: jax version, "
                   "mesh, config digest) — budget bumps land as reviewable "
                   "diffs, never silently")
    p.add_argument("--list-passes", action="store_true",
                   help="list registered passes and exit")
    p.add_argument("--json", metavar="FILE",
                   help="also write the dtf-lint-report/1 JSON here "
                   "('-' for stdout)")
    p.add_argument("--format", choices=("table", "json"), default="table",
                   help="stdout format (default: table)")
    p.add_argument("--suppressions", default=str(DEFAULT_SUPPRESSIONS),
                   help="suppression file (default: tools/graftcheck/"
                   "suppressions.txt)")
    return p


def select_passes(args, changed: set[str] | None) -> list[registry.PassInfo]:
    if args.passes:
        return [registry.get_pass(pid) for pid in args.passes]
    infos = list(registry.PASSES.values())
    if args.layer:
        infos = [p for p in infos if p.layer == args.layer]
    elif args.changed and not getattr(args, "trace", False):
        # Trace layers (jaxpr/hlo) compile the real step — seconds, not
        # milliseconds; the fast pre-commit loop is AST-only unless the
        # caller opts back in with --trace (main() prints the skip count).
        infos = [p for p in infos if p.layer == registry.LAYER_AST]
    if changed is not None:
        infos = [p for p in infos if p.relevant_for_changed(changed)]
    return infos


def skipped_trace_passes(args, changed: set[str]) -> list[registry.PassInfo]:
    """The trace (jaxpr/hlo) passes a --changed run dropped — the explicit
    notice keeps the fast path honest about what it did NOT check."""
    if not args.changed or args.layer or args.passes \
            or getattr(args, "trace", False):
        return []
    return [p for p in registry.PASSES.values()
            if p.layer in registry.TRACE_LAYERS
            and p.relevant_for_changed(changed)]


def run_passes(ctx: RepoContext,
               infos: list[registry.PassInfo]) -> list[Finding]:
    findings: list[Finding] = []
    for info in infos:
        try:
            findings.extend(info.fn(ctx))
        except Exception as exc:  # a crashed audit must not read as clean
            findings.append(Finding(
                info.pass_id, "pass", f"pass crashed: {exc!r}",
                severity=fmod.SEVERITY_INTERNAL))
    return findings


def format_table(report: dict, infos: list[registry.PassInfo]) -> str:
    lines = []
    rows = [f for f in report["findings"] if not f["suppressed"]]
    sup = [f for f in report["findings"] if f["suppressed"]]
    if rows:
        w_pass = max(len(f["pass_id"]) for f in rows)
        w_where = max(len(f["where"]) for f in rows)
        for f in sorted(rows, key=lambda f: (f["pass_id"], f["where"])):
            tag = " [internal]" if f["severity"] == fmod.SEVERITY_INTERNAL else ""
            lines.append(f"{f['pass_id']:<{w_pass}}  {f['where']:<{w_where}}"
                         f"  {f['message']}{tag}")
        lines.append("")
    c = report["counts"]
    lines.append(
        f"graftcheck: {len(infos)} pass(es) run, {c['findings']} finding(s)"
        f" ({c['internal_errors']} internal), {c['suppressed']} suppressed")
    for f in sup:
        lines.append(f"  suppressed: {f['pass_id']} {f['where']} — "
                     f"{f['justification']}")
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)

    if args.list_passes:
        for info in sorted(registry.PASSES.values(),
                           key=lambda p: (p.layer, p.pass_id)):
            print(f"{info.pass_id:<26} [{info.layer}]  {info.description}")
        return EXIT_CLEAN

    root = pathlib.Path(args.root).resolve()

    if args.update_budgets:
        from tools.graftcheck import hlo_passes
        ctx = RepoContext(root)
        try:
            path = hlo_passes.write_budgets(ctx)
        except Exception as exc:
            print(f"graftcheck: --update-budgets failed: {exc!r}",
                  file=sys.stderr)
            return EXIT_INTERNAL
        print(f"graftcheck: wrote {path} — review and commit the diff")
        return EXIT_CLEAN

    changed = None
    if args.changed:
        try:
            changed = git_changed_files(root)
        except RuntimeError as exc:
            print(f"graftcheck: --changed needs git: {exc}", file=sys.stderr)
            return EXIT_USAGE

    try:
        infos = select_passes(args, changed)
    except KeyError as exc:
        print(f"graftcheck: {exc.args[0]}", file=sys.stderr)
        return EXIT_USAGE

    if changed is not None:
        skipped = skipped_trace_passes(args, changed)
        if skipped:
            print(f"graftcheck: {len(skipped)} trace passes skipped in "
                  f"--changed mode ({', '.join(sorted(p.pass_id for p in skipped))})"
                  f" — add --trace to run them")

    ctx = RepoContext(root, changed=changed)
    findings = run_passes(ctx, infos)
    sups, sup_findings = fmod.load_suppressions(args.suppressions)
    findings.extend(sup_findings)
    full_run = (changed is None and not args.passes and not args.layer)
    stale = fmod.apply_suppressions(
        findings, sups, suppression_file=pathlib.Path(args.suppressions).name,
        stale_check_ids=None if full_run else {i.pass_id for i in infos})
    if changed is None:  # --changed sees partial files; can't judge staleness
        findings.extend(stale)

    report = fmod.build_report(findings, [i.pass_id for i in infos], root)
    if args.format == "json":
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        print(format_table(report, infos))
    if args.json:
        payload = json.dumps(report, indent=2, sort_keys=True)
        if args.json == "-":
            if args.format != "json":
                print(payload)
        else:
            pathlib.Path(args.json).write_text(payload + "\n")

    if report["counts"]["internal_errors"]:
        return EXIT_INTERNAL
    if report["counts"]["findings"]:
        return EXIT_FINDINGS
    return EXIT_CLEAN
