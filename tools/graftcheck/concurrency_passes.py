"""Layer-1 (AST) concurrency-contract passes.

Four package modules spawn background threads (ckpt/async_saver,
serve/engine, serve/server, data/infeed) and three more share state with
them under locks (core/telemetry, core/goodput, core/faults) — all under
conventions no ordinary linter knows about. These two passes make the
async-saver contract — the reference implementation in
``ckpt/async_saver.py`` — machine-checked across the package:

  * ``thread-lifecycle`` — every ``threading.Thread`` must (1) carry a
    ``dtf-*`` name (statically resolvable, so ``ps``/py-spy dumps read as
    ours), (2) be daemon or joined somewhere in its module (a non-daemon
    unjoined thread hangs process exit on a stuck write), and (3) have a
    target that funnels exceptions into a typed error surfaced on the
    owning thread — a broad except handler whose bound exception ESCAPES
    (stored, passed to a call, or re-raised), not one that only logs.
    ``ThreadPoolExecutor`` gets the name rule via ``thread_name_prefix``.
  * ``lock-discipline`` — within a class that starts threads, a field
    assigned from two or more thread groups (the main/API group plus each
    thread target's reachable methods) must only be written under one of
    the class's locks (``with self.<lock>`` lexically, or inside a method
    named ``*_locked`` — the repo's held-lock naming convention) or be an
    inherently thread-safe handoff type (``queue.Queue``, ``Event``, …).
    Single-writer fields stay unflagged: the contract is about racing
    writers, not about wrapping every counter.

Both are pure-``ast`` passes (no jax import) and run in the ``--changed``
pre-commit loop. File-level helpers (``scan_thread_lifecycle``,
``scan_lock_discipline``) are public for the fixture tests.
"""

from __future__ import annotations

import ast

from tools.graftcheck.context import RepoContext
from tools.graftcheck.findings import Finding
from tools.graftcheck.registry import LAYER_AST, register

THREAD_NAME_PREFIX = "dtf-"

_LOCK_FACTORIES = frozenset({"Lock", "RLock", "Condition"})
_EXEMPT_FACTORIES = frozenset({
    "Queue", "SimpleQueue", "LifoQueue", "PriorityQueue",
    "Event", "Semaphore", "BoundedSemaphore", "Barrier",
})
_BROAD_EXC = frozenset({"Exception", "BaseException"})
_LOG_ROOTS = frozenset({"log", "logger", "logging"})
_LOCKED_SUFFIX = "_locked"


# ----------------------------------------------------------- AST helpers --
def _call_name(node: ast.expr) -> str | None:
    """Trailing name of a call target: ``threading.Thread`` → "Thread"."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _kwarg(call: ast.Call, name: str) -> ast.expr | None:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _is_self_attr(node: ast.expr) -> ast.Attribute | None:
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node
    return None


def _module_str_consts(tree: ast.Module) -> dict[str, str]:
    out = {}
    for node in tree.body:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)):
            out[node.targets[0].id] = node.value.value
    return out


def _init_param_defaults(cls: ast.ClassDef) -> dict[str, ast.expr]:
    """kwarg name → default expr for the class's ``__init__``."""
    for node in cls.body:
        if isinstance(node, ast.FunctionDef) and node.name == "__init__":
            a = node.args
            out: dict[str, ast.expr] = {}
            pos = a.posonlyargs + a.args
            for arg, default in zip(pos[len(pos) - len(a.defaults):],
                                    a.defaults):
                out[arg.arg] = default
            for arg, default in zip(a.kwonlyargs, a.kw_defaults):
                if default is not None:
                    out[arg.arg] = default
            return out
    return {}


def _init_self_assigns(cls: ast.ClassDef) -> dict[str, ast.expr]:
    """``self.X = expr`` assignments in ``__init__``."""
    out: dict[str, ast.expr] = {}
    for node in cls.body:
        if isinstance(node, ast.FunctionDef) and node.name == "__init__":
            for sub in ast.walk(node):
                if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                    attr = _is_self_attr(sub.targets[0])
                    if attr is not None:
                        out[attr.attr] = sub.value
    return out


def resolve_thread_name(expr: ast.expr, tree: ast.Module,
                        cls: ast.ClassDef | None) -> str | None:
    """Statically resolve a ``name=``/``thread_name_prefix=`` expression:
    literal → module constant → ``self.attr`` set in ``__init__`` from a
    parameter default (the async_saver chain). None = not resolvable."""
    consts = _module_str_consts(tree)
    defaults = _init_param_defaults(cls) if cls is not None else {}
    self_assigns = _init_self_assigns(cls) if cls is not None else {}

    def resolve(node: ast.expr, depth: int) -> str | None:
        if depth > 4 or node is None:
            return None
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value
        if isinstance(node, ast.Name):
            if node.id in consts:
                return consts[node.id]
            if node.id in defaults:
                return resolve(defaults[node.id], depth + 1)
            return None
        attr = _is_self_attr(node)
        if attr is not None and attr.attr in self_assigns:
            return resolve(self_assigns[attr.attr], depth + 1)
        return None

    return resolve(expr, 0)


def _enclosing_maps(tree: ast.Module):
    """(node → enclosing ClassDef, node → enclosing FunctionDef chain,
    innermost first). The chain matters: a Thread() call inside a signal
    handler may target a sibling defined one function up."""
    cls_of: dict[ast.AST, ast.ClassDef] = {}
    fns_of: dict[ast.AST, tuple[ast.FunctionDef, ...]] = {}

    def walk(node, cls, fns):
        for child in ast.iter_child_nodes(node):
            c, f = cls, fns
            if isinstance(child, ast.ClassDef):
                c = child
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                f = (child,) + fns
            if cls is not None:
                cls_of[child] = cls
            if fns:
                fns_of[child] = fns
            walk(child, c, f)

    walk(tree, None, ())
    return cls_of, fns_of


def _resolve_target_fn(target: ast.expr, tree: ast.Module,
                       cls: ast.ClassDef | None,
                       enclosing_fns: tuple[ast.FunctionDef, ...]
                       ) -> ast.FunctionDef | None:
    """The FunctionDef a ``target=`` expression names: ``self.meth``, a
    nested function in any enclosing function (innermost scope wins), or
    a module-level def."""
    attr = _is_self_attr(target)
    if attr is not None and cls is not None:
        for node in cls.body:
            if isinstance(node, ast.FunctionDef) and node.name == attr.attr:
                return node
    if isinstance(target, ast.Name):
        scopes = [fn.body for fn in enclosing_fns]
        scopes.append(tree.body)
        for body in scopes:
            for node in body:
                if (isinstance(node, ast.FunctionDef)
                        and node.name == target.id):
                    return node
    return None


def _is_log_call(call: ast.Call) -> bool:
    func = call.func
    return (isinstance(func, ast.Attribute)
            and isinstance(func.value, ast.Name)
            and func.value.id in _LOG_ROOTS)


def _exception_escapes(handler: ast.ExceptHandler) -> bool:
    """Does the bound exception leave the handler — assigned somewhere,
    passed into a (non-logging) call, or re-raised? Logging alone is the
    silent-daemon-stderr failure mode the contract forbids."""
    bound = handler.name
    if not bound:
        return False

    def contains_bound(node: ast.AST) -> bool:
        return any(isinstance(n, ast.Name) and n.id == bound
                   for n in ast.walk(node))

    for stmt in handler.body:
        for node in ast.walk(stmt):
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                if contains_bound(node.value):
                    return True
            elif isinstance(node, ast.Raise):
                if ((node.exc is not None and contains_bound(node.exc))
                        or (node.cause is not None
                            and contains_bound(node.cause))):
                    return True
            elif isinstance(node, ast.Call) and not _is_log_call(node):
                if any(contains_bound(a) for a in node.args):
                    return True
    return False


def _own_nodes(fn: ast.FunctionDef):
    """Nodes of ``fn`` excluding nested function bodies (those are
    separate audit targets)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _has_exception_funnel(fn: ast.FunctionDef) -> bool:
    """A broad except handler (Exception/BaseException) in the target
    whose bound exception escapes."""
    for node in _own_nodes(fn):
        if not isinstance(node, ast.Try):
            continue
        for handler in node.handlers:
            types = []
            if isinstance(handler.type, ast.Tuple):
                types = [_call_name(e) for e in handler.type.elts]
            elif handler.type is not None:
                types = [_call_name(handler.type)]
            if not set(types) & _BROAD_EXC:
                continue
            if _exception_escapes(handler):
                return True
    return False


# ---------------------------------------------------- thread-lifecycle --
def scan_thread_lifecycle(rel: str, tree: ast.Module) -> list[Finding]:
    out = []
    cls_of, fns_of = _enclosing_maps(tree)
    source_has_join: set[str] = set()
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "join"):
            owner = node.func.value
            attr = _is_self_attr(owner)
            if attr is not None:
                source_has_join.add(attr.attr)
            elif isinstance(owner, ast.Name):
                source_has_join.add(owner.id)

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        callee = _call_name(node.func)
        where = f"{rel}:{node.lineno}"
        cls = cls_of.get(node)
        enclosing = fns_of.get(node, ())

        if callee == "ThreadPoolExecutor":
            prefix = _kwarg(node, "thread_name_prefix")
            resolved = (resolve_thread_name(prefix, tree, cls)
                        if prefix is not None else None)
            if resolved is None or not resolved.startswith(
                    THREAD_NAME_PREFIX):
                out.append(Finding(
                    "thread-lifecycle", where,
                    f"ThreadPoolExecutor needs thread_name_prefix="
                    f"'{THREAD_NAME_PREFIX}*' (got "
                    f"{resolved!r}) so its workers read as ours in "
                    f"thread dumps"))
            continue
        if callee != "Thread":
            continue

        name_expr = _kwarg(node, "name")
        if name_expr is None:
            out.append(Finding(
                "thread-lifecycle", where,
                f"threading.Thread without name= — background threads "
                f"must carry a '{THREAD_NAME_PREFIX}*' name so thread "
                f"dumps attribute them"))
        else:
            resolved = resolve_thread_name(name_expr, tree, cls)
            if resolved is None:
                out.append(Finding(
                    "thread-lifecycle", where,
                    f"thread name is not statically resolvable — use a "
                    f"'{THREAD_NAME_PREFIX}*' literal, module constant, "
                    f"or __init__ parameter default"))
            elif not resolved.startswith(THREAD_NAME_PREFIX):
                out.append(Finding(
                    "thread-lifecycle", where,
                    f"thread name {resolved!r} lacks the "
                    f"'{THREAD_NAME_PREFIX}' prefix the module contract "
                    f"requires"))

        daemon = _kwarg(node, "daemon")
        is_daemon = (isinstance(daemon, ast.Constant)
                     and daemon.value is True)
        if not is_daemon:
            # Non-daemon is fine only when the module joins the thread:
            # find the binding this Thread lands in.
            joined = False
            parent_assign = None
            for cand in ast.walk(tree):
                if isinstance(cand, ast.Assign) and any(
                        n is node for n in ast.walk(cand.value)):
                    parent_assign = cand
                    break
            if parent_assign is not None:
                for tgt in parent_assign.targets:
                    attr = _is_self_attr(tgt)
                    if attr is not None and attr.attr in source_has_join:
                        joined = True
                    elif (isinstance(tgt, ast.Name)
                          and tgt.id in source_has_join):
                        joined = True
            if not joined:
                out.append(Finding(
                    "thread-lifecycle", where,
                    "thread is neither daemon=True nor joined in this "
                    "module — a stuck write would hang process exit"))

        target = _kwarg(node, "target")
        if target is None:
            out.append(Finding(
                "thread-lifecycle", where,
                "Thread without target= cannot be audited for the "
                "exception-funnel contract"))
            continue
        target_fn = _resolve_target_fn(target, tree, cls, enclosing)
        if target_fn is None:
            out.append(Finding(
                "thread-lifecycle", where,
                "thread target is not statically resolvable (method, "
                "nested function, or module function) — the "
                "exception-funnel contract cannot be audited"))
        elif not _has_exception_funnel(target_fn):
            out.append(Finding(
                "thread-lifecycle", where,
                f"thread target {target_fn.name!r} does not funnel "
                f"exceptions: it needs a broad except handler whose bound "
                f"exception escapes into a typed error surfaced on the "
                f"owning thread (ckpt/async_saver.py is the reference), "
                f"not a log-and-vanish"))
    return out


# ------------------------------------------------------ lock-discipline --
def _self_calls(fn: ast.FunctionDef) -> set[str]:
    out = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            attr = _is_self_attr(node.func)
            if attr is not None:
                out.add(attr.attr)
    return out


def _attr_writes(fn: ast.FunctionDef, lock_attrs: set[str]
                 ) -> list[tuple[str, int, bool]]:
    """(attr, lineno, under_lock) for every ``self.X = ...`` store in
    ``fn``, excluding nested defs. ``under_lock`` is lexical: inside a
    ``with self.<lock>`` block, or the whole method when its name carries
    the ``*_locked`` held-lock convention."""
    writes: list[tuple[str, int, bool]] = []
    held_by_name = fn.name.endswith(_LOCKED_SUFFIX)

    def targets_of(stmt) -> list[ast.expr]:
        if isinstance(stmt, ast.Assign):
            flat = []
            for t in stmt.targets:
                flat.extend(t.elts if isinstance(t, ast.Tuple) else [t])
            return flat
        if isinstance(stmt, ast.AugAssign):
            return [stmt.target]
        return []

    def visit(node, under: bool):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            now = under
            if isinstance(child, ast.With):
                for item in child.items:
                    attr = _is_self_attr(item.context_expr)
                    if attr is not None and attr.attr in lock_attrs:
                        now = True
            for tgt in targets_of(child):
                attr = _is_self_attr(tgt)
                if attr is not None:
                    writes.append((attr.attr, child.lineno,
                                   now or held_by_name))
            visit(child, now)

    visit(fn, held_by_name)
    return writes


def scan_lock_discipline(rel: str, tree: ast.Module) -> list[Finding]:
    out = []
    for cls in [n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)]:
        methods = {n.name: n for n in cls.body
                   if isinstance(n, ast.FunctionDef)}
        # Lock and exempt attrs from any `self.X = factory()` assignment.
        lock_attrs: set[str] = set()
        exempt_attrs: set[str] = set()
        for node in ast.walk(cls):
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
                continue
            attr = _is_self_attr(node.targets[0])
            if attr is None or not isinstance(node.value, ast.Call):
                continue
            factory = _call_name(node.value.func)
            if factory in _LOCK_FACTORIES:
                lock_attrs.add(attr.attr)
            elif factory in _EXEMPT_FACTORIES:
                exempt_attrs.add(attr.attr)

        # Background entries: Thread(target=self.M | nested fn) started in
        # this class. Nested-function targets are audited as their own
        # group.
        entries: list[ast.FunctionDef] = []
        for node in ast.walk(cls):
            if (isinstance(node, ast.Call)
                    and _call_name(node.func) == "Thread"):
                target = _kwarg(node, "target")
                if target is None:
                    continue
                attr = _is_self_attr(target)
                if attr is not None and attr.attr in methods:
                    entries.append(methods[attr.attr])
                elif isinstance(target, ast.Name):
                    for meth in methods.values():
                        for sub in ast.walk(meth):
                            if (isinstance(sub, ast.FunctionDef)
                                    and sub.name == target.id):
                                entries.append(sub)
        if not entries:
            continue

        def reachable(fn: ast.FunctionDef) -> set[str]:
            seen: set[str] = set()
            frontier = [fn]
            while frontier:
                cur = frontier.pop()
                for callee in _self_calls(cur):
                    if callee not in seen and callee in methods:
                        seen.add(callee)
                        frontier.append(methods[callee])
            return seen

        bg_names = [{e.name} | reachable(e) for e in entries]
        all_bg = set().union(*bg_names)
        # Main group: public surface — methods that are not thread
        # entries — plus everything they reach. __init__ is excluded:
        # it runs before any thread starts.
        entry_names = {e.name for e in entries}
        seeds = [m for name, m in methods.items()
                 if name not in entry_names and name != "__init__"
                 and name not in all_bg]
        main_names: set[str] = set()
        for seed in seeds:
            main_names |= {seed.name} | reachable(seed)
        main_names -= entry_names
        main_names.discard("__init__")

        # attr → {group index} and the write sites (lineno → under_lock);
        # a method shared by several groups records each site once.
        groups_of: dict[str, set[int]] = {}
        sites: dict[str, dict[int, bool]] = {}

        def record(fn: ast.FunctionDef, group: int):
            for attr, lineno, under in _attr_writes(fn, lock_attrs):
                if attr in lock_attrs or attr in exempt_attrs:
                    continue
                groups_of.setdefault(attr, set()).add(group)
                sites.setdefault(attr, {})[lineno] = under

        for name in main_names:
            record(methods[name], 0)
        for i, (entry, names) in enumerate(zip(entries, bg_names), start=1):
            if entry.name in methods:
                for name in names:
                    record(methods[name], i)
            else:  # nested-function target: its body plus reached methods
                record(entry, i)
                for name in reachable(entry):
                    record(methods[name], i)

        for attr in sorted(groups_of):
            if len(groups_of[attr]) < 2:
                continue
            if not lock_attrs:
                out.append(Finding(
                    "lock-discipline",
                    f"{rel}:{min(sites[attr])}",
                    f"{cls.name}.{attr} is written from {len(groups_of[attr])} "
                    f"thread groups but the class owns no lock "
                    f"(threading.Lock/RLock/Condition) to serialize them"))
                continue
            for lineno, under in sorted(sites[attr].items()):
                if not under:
                    out.append(Finding(
                        "lock-discipline", f"{rel}:{lineno}",
                        f"{cls.name}.{attr} is written from multiple "
                        f"threads but this write is outside `with "
                        f"self.<lock>` (and not in a *{_LOCKED_SUFFIX} "
                        f"method) — racing writers corrupt the field"))
    return out


# ----------------------------------------------------------------- passes --
@register(
    "thread-lifecycle", LAYER_AST,
    "every threading.Thread is daemon-or-joined, carries a dtf-* name, "
    "and its target funnels exceptions into a typed error on the owning "
    "thread (the async-saver contract, generalized)")
def thread_lifecycle_pass(ctx: RepoContext) -> list[Finding]:
    findings = []
    for path in ctx.pkg_files():
        if not ctx.selected(path):
            continue
        findings.extend(scan_thread_lifecycle(ctx.rel(path), ctx.tree(path)))
    return findings


@register(
    "lock-discipline", LAYER_AST,
    "fields written from >=2 thread groups in a class must be written "
    "under the class's lock (or be Queue/Event handoff types)")
def lock_discipline_pass(ctx: RepoContext) -> list[Finding]:
    findings = []
    for path in ctx.pkg_files():
        if not ctx.selected(path):
            continue
        findings.extend(scan_lock_discipline(ctx.rel(path), ctx.tree(path)))
    return findings
