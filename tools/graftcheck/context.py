"""RepoContext — the filesystem view every pass reads through.

Centralizes path layout (package dir, tests, docs, scripts), caches parsed
ASTs, and carries the ``--changed`` file filter. The package name is a
parameter so pass logic can be exercised against the fixture mini-repos
under ``tests/graftcheck_fixtures/`` with zero special-casing.
"""

from __future__ import annotations

import ast
import pathlib
import subprocess

DEFAULT_PACKAGE = "distributed_tensorflow_framework_tpu"


class RepoContext:
    def __init__(
        self,
        root: str | pathlib.Path,
        package: str = DEFAULT_PACKAGE,
        changed: set[str] | None = None,
    ):
        self.root = pathlib.Path(root).resolve()
        self.package = package
        self.changed = changed  # repo-relative posix paths; None = everything
        self._src: dict[pathlib.Path, str] = {}
        self._ast: dict[pathlib.Path, ast.Module] = {}

    # ------------------------------------------------------------ layout --
    @property
    def pkg_dir(self) -> pathlib.Path:
        return self.root / self.package

    @property
    def tests_dir(self) -> pathlib.Path:
        return self.root / "tests"

    @property
    def docs_dir(self) -> pathlib.Path:
        return self.root / "docs"

    def rel(self, path: pathlib.Path) -> str:
        return path.resolve().relative_to(self.root).as_posix()

    # ------------------------------------------------------------- files --
    def pkg_files(self) -> list[pathlib.Path]:
        return sorted(p for p in self.pkg_dir.rglob("*.py")
                      if "__pycache__" not in p.parts)

    def test_files(self) -> list[pathlib.Path]:
        """Top-level test modules only — fixture mini-repos under
        tests/graftcheck_fixtures/ deliberately contain violating code and
        must never be scanned as part of the real repo."""
        if not self.tests_dir.is_dir():
            return []
        return sorted(self.tests_dir.glob("test_*.py"))

    def script_files(self) -> list[pathlib.Path]:
        files = sorted((self.root / "scripts").glob("*.py"))
        for name in ("bench.py", "train.py"):
            p = self.root / name
            if p.exists():
                files.append(p)
        return files

    def doc_files(self) -> list[pathlib.Path]:
        files = sorted(self.docs_dir.glob("*.md")) if self.docs_dir.is_dir() else []
        readme = self.root / "README.md"
        if readme.exists():
            files.append(readme)
        return files

    def selected(self, path: pathlib.Path) -> bool:
        """Changed-mode filter for per-file passes."""
        if self.changed is None:
            return True
        return self.rel(path) in self.changed

    # ------------------------------------------------------------ parsing --
    def source(self, path: pathlib.Path) -> str:
        path = path.resolve()
        if path not in self._src:
            self._src[path] = path.read_text()
        return self._src[path]

    def tree(self, path: pathlib.Path) -> ast.Module:
        path = path.resolve()
        if path not in self._ast:
            self._ast[path] = ast.parse(self.source(path), filename=str(path))
        return self._ast[path]


def git_changed_files(root: str | pathlib.Path) -> set[str]:
    """Working-tree delta for ``--changed`` mode: unstaged + staged +
    untracked (git's own exclude rules keep __pycache__ etc. out)."""
    root = str(root)
    out: set[str] = set()
    for args in (
        ["git", "diff", "--name-only", "HEAD"],
        ["git", "ls-files", "--others", "--exclude-standard"],
    ):
        res = subprocess.run(args, cwd=root, capture_output=True, text=True)
        if res.returncode != 0:
            raise RuntimeError(
                f"{' '.join(args)} failed (rc={res.returncode}): "
                f"{res.stderr.strip()}")
        out.update(line.strip() for line in res.stdout.splitlines()
                   if line.strip())
    return out
