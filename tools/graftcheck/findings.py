"""Finding/suppression model and the ``dtf-lint-report/1`` JSON schema.

A finding is (pass_id, where, message): ``where`` is a repo-relative
``path:line`` for AST-layer findings and a ``trace:<name_stack>`` provenance
string for jaxpr-layer ones. Suppressions live in a pipe-separated file
(default ``tools/graftcheck/suppressions.txt``); every entry carries a
REQUIRED justification string and must match at least one live finding —
stale entries are themselves findings, so the file can't rot.
"""

from __future__ import annotations

import dataclasses
import fnmatch
import json
import pathlib
from dataclasses import dataclass, field

REPORT_SCHEMA = "dtf-lint-report/1"

SEVERITY_ERROR = "error"
SEVERITY_INTERNAL = "internal-error"
_SEVERITIES = (SEVERITY_ERROR, SEVERITY_INTERNAL)

# The suppression machinery reports its own problems under this pass id.
SUPPRESSIONS_PASS = "suppressions"


@dataclass
class Finding:
    pass_id: str
    where: str
    message: str
    severity: str = SEVERITY_ERROR
    suppressed: bool = False
    justification: str = ""

    @property
    def fingerprint(self) -> str:
        return f"{self.pass_id}|{self.where}"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "Finding":
        return cls(**d)


@dataclass
class Suppression:
    pass_id: str          # exact pass id, or "*"
    pattern: str          # fnmatch glob over Finding.where
    justification: str
    line_no: int
    uses: int = field(default=0)

    def matches(self, f: Finding) -> bool:
        if self.pass_id != "*" and self.pass_id != f.pass_id:
            return False
        return fnmatch.fnmatchcase(f.where, self.pattern)


def load_suppressions(
    path: str | pathlib.Path,
) -> tuple[list[Suppression], list[Finding]]:
    """Parse the suppression file. Malformed lines (wrong field count or a
    missing justification) come back as findings — a suppression without a
    recorded reason is exactly the silent convention this tool replaces."""
    path = pathlib.Path(path)
    sups: list[Suppression] = []
    findings: list[Finding] = []
    if not path.exists():
        return sups, findings
    for i, raw in enumerate(path.read_text().splitlines(), start=1):
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        parts = [p.strip() for p in line.split("|")]
        where = f"{path.name}:{i}"
        if len(parts) != 3:
            findings.append(Finding(
                SUPPRESSIONS_PASS, where,
                f"malformed suppression (want 'pass-id | where-glob | "
                f"justification'): {line!r}"))
            continue
        pass_id, pattern, justification = parts
        if not pass_id or not pattern or not justification:
            findings.append(Finding(
                SUPPRESSIONS_PASS, where,
                f"suppression missing a field (the justification is "
                f"mandatory): {line!r}"))
            continue
        sups.append(Suppression(pass_id, pattern, justification, i))
    return sups, findings


def apply_suppressions(
    findings: list[Finding],
    sups: list[Suppression],
    suppression_file: str = "suppressions.txt",
    stale_check_ids: set[str] | None = None,
) -> list[Finding]:
    """Mark suppressed findings in place; return extra findings for stale
    (never-matched) suppression entries. ``stale_check_ids`` limits the
    staleness report to suppressions for those pass ids (partial runs —
    ``--layer``/``--pass`` — can't judge entries for passes that didn't
    run); None means a full run, where every entry must earn its keep."""
    for f in findings:
        if f.severity == SEVERITY_INTERNAL:
            continue  # infrastructure failures cannot be suppressed
        for s in sups:
            if s.matches(f):
                f.suppressed = True
                f.justification = s.justification
                s.uses += 1
                break
    extra = []
    for s in sups:
        if stale_check_ids is not None and s.pass_id not in stale_check_ids:
            continue  # "*" entries are only judged on full runs
        if s.uses == 0:
            extra.append(Finding(
                SUPPRESSIONS_PASS, f"{suppression_file}:{s.line_no}",
                f"stale suppression — no live finding matches "
                f"({s.pass_id} | {s.pattern}); delete it"))
    return extra


def build_report(
    findings: list[Finding],
    passes_run: list[str],
    root: str | pathlib.Path,
) -> dict:
    active = [f for f in findings if not f.suppressed]
    return {
        "schema": REPORT_SCHEMA,
        "root": str(root),
        "passes_run": sorted(passes_run),
        "findings": [f.to_dict() for f in findings],
        "counts": {
            "findings": len(active),
            "suppressed": sum(1 for f in findings if f.suppressed),
            "internal_errors": sum(
                1 for f in active if f.severity == SEVERITY_INTERNAL),
        },
    }


def validate_report(d: dict) -> list[str]:
    """Structural validation of a dtf-lint-report/1 object (the shape
    consumers like CI dashboards may rely on). Returns problem strings."""
    errs: list[str] = []
    if d.get("schema") != REPORT_SCHEMA:
        errs.append(f"schema must be {REPORT_SCHEMA!r}, got {d.get('schema')!r}")
    for key, typ in (("root", str), ("passes_run", list),
                     ("findings", list), ("counts", dict)):
        if not isinstance(d.get(key), typ):
            errs.append(f"{key} must be {typ.__name__}")
    for i, f in enumerate(d.get("findings") or []):
        if not isinstance(f, dict):
            errs.append(f"findings[{i}] must be an object")
            continue
        for key in ("pass_id", "where", "message", "severity"):
            if not isinstance(f.get(key), str) or not f.get(key):
                errs.append(f"findings[{i}].{key} must be a non-empty string")
        if f.get("severity") not in _SEVERITIES:
            errs.append(
                f"findings[{i}].severity must be one of {_SEVERITIES}")
        if not isinstance(f.get("suppressed"), bool):
            errs.append(f"findings[{i}].suppressed must be a bool")
    counts = d.get("counts") or {}
    for key in ("findings", "suppressed", "internal_errors"):
        if not isinstance(counts.get(key), int):
            errs.append(f"counts.{key} must be an int")
    return errs


def round_trip(d: dict) -> dict:
    """JSON-encode and decode (the report must survive serialization)."""
    return json.loads(json.dumps(d, sort_keys=True))
