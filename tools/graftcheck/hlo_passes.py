"""Layer-3 (compiled-HLO) passes.

The jaxpr layer stops where XLA starts: GSPMD resharding, donation that
dies in lowering, and the program's real byte footprint are all decided
AFTER the trace. These audits ``lower().compile()`` the real train step
(and the serve forward) on the 8-device CPU mesh and read the optimized
artifact itself:

  * ``hlo-reshard-census`` — every all-gather/all-reduce/all-to-all/
    collective-permute in the optimized module must map to a
    jaxpr-declared collective. XLA may FUSE or decompose declared
    collectives (fewer ops than the jaxpr is fine — e.g. CPU lowers
    all-to-all away entirely), but an EXTRA op is a GSPMD-inserted
    reshard: reported with its shape, byte count, and the sharding/op
    provenance the compiler recorded for it.
  * ``hlo-donation-survival`` — the ``input_output_alias`` table of the
    compiled executable must carry one entry per train-state leaf.
    Donation can survive tracing (the jaxpr-layer check) and still be
    dropped in lowering; this is the check against the artifact that
    actually runs.
  * ``hlo-memory-budget`` — ``compiled.memory_analysis()`` byte figures
    (the same fields core/memstats.py reports) gated against the
    checked-in ``configs/hlo_budgets.json`` with a two-sided tolerance
    band: over budget is an HBM regression caught before a chip window
    is spent, far under budget is a stale budget that must be
    regenerated (``scripts/graftcheck.py --update-budgets``) so the bump
    shows up as a reviewable diff.

Compiled artifacts are built once per process (``_COMPILED_CACHE``) on
top of the jaxpr layer's probe cache, so the tier-1 self-audit and the
dedicated tests share the compile work.
"""

from __future__ import annotations

import hashlib
import json
import pathlib
import re

from tools.graftcheck import jaxpr_passes as jp
from tools.graftcheck.context import RepoContext
from tools.graftcheck.findings import SEVERITY_INTERNAL, Finding
from tools.graftcheck.registry import LAYER_HLO, register

BUDGETS_SCHEMA = "dtf-hlo-budgets/1"
BUDGETS_RELPATH = pathlib.Path("configs") / "hlo_budgets.json"
DEFAULT_TOLERANCE = 0.10
# Absolute slack floor so a few-KiB layout wobble on a small program
# can't flap the gate (the band is max(budget*tol, this)).
MIN_SLACK_BYTES = 64 * 1024

BUDGET_FIELDS = ("argument_bytes", "output_bytes", "temp_bytes",
                 "peak_bytes_est")

# Probes whose compiled module gets the reshard census: shard_map probes
# declare every collective in the jaxpr, so an extra optimized-HLO
# collective is compiler-inserted. jit-mode probes hand XLA an
# unpartitioned program where the grad all-reduce is legitimately
# compiler-owned — a census there would be all noise.
CENSUS_PROBES = jp.CENSUS_PROBES
# Probes whose compiled module must keep the donation aliases.
DONATION_PROBES = ("jit_f32",) + jp.CENSUS_PROBES
# program name in hlo_budgets.json → probe ("serve" = the serve forward).
BUDGET_PROGRAMS = {
    "train_step:jit_f32": "jit_f32",
    # The bf16 precision-policy step, budgeted NEXT TO its f32 twin so a
    # layer change that silently re-widens activations shows up as an
    # over-budget diff before a chip window is spent. CPU-gate caveat,
    # measured (PERFORMANCE.md "Flipping the bound"): this backend has no
    # native bf16 kernels, so float normalization stages every bf16
    # dot/conv through f32 copies and the probe's temp bytes read HIGHER
    # than f32's (840,288 vs 521,824 at the 2026-08 regeneration) — the
    # entry gates regressions of the bf16 program against itself; the
    # halved-activation claim is a TPU number, carried by the bench
    # hbm_peak_bytes_per_chip mirror and the §13 precision-ladder A/B.
    "train_step:jit_bf16_policy": "jit_bf16_policy",
    "train_step:shard_dp_fsdp": "shard_dp_fsdp",
    "train_step:shard_q8_ef": "shard_q8_ef",
    "train_step:shard_zero": "shard_zero",
    "train_step:shard_zero_fused": "shard_zero_fused",
    "serve_forward:lenet5": "serve",
}

# jaxpr collective primitive → optimized-HLO opcode.
PRIM_TO_HLO_OP = {
    "psum": "all-reduce",
    "pmin": "all-reduce",
    "pmax": "all-reduce",
    "all_gather": "all-gather",
    "reduce_scatter": "reduce-scatter",
    "all_to_all": "all-to-all",
    "ppermute": "collective-permute",
}

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "f8e4m3fn": 1, "f8e5m2": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

_INSTR = re.compile(
    r"^\s*(?:ROOT )?%?[\w.-]+ = (?P<rtype>.*?) "
    r"(?P<op>all-reduce-start|all-gather-start|collective-permute-start|"
    r"all-reduce|all-gather|all-to-all|collective-permute|reduce-scatter)"
    r"\(", re.M)
_SHAPE = re.compile(r"(\w+)\[([\d,]*)\]")
_SHARDING = re.compile(r"sharding=(\{[^}]*\})")
_OP_NAME = re.compile(r'op_name="([^"]+)"')


# ------------------------------------------------------------ HLO parsing --
def shape_bytes(rtype: str) -> int:
    """Total bytes of an HLO result type (sums tuple elements)."""
    total = 0
    for dtype, dims in _SHAPE.findall(rtype):
        if dtype not in _DTYPE_BYTES:
            continue  # token[...] that isn't a shape (e.g. layout braces)
        n = 1
        for d in dims.split(","):
            if d.strip():
                n *= int(d)
        total += n * _DTYPE_BYTES[dtype]
    return total


def collect_collectives(hlo_text: str) -> list[dict]:
    """Collective instructions of the optimized module, with the result
    shape, byte count, and the sharding/op_name provenance XLA kept.
    ``*-start`` async forms count once (the ``*-done`` halves don't
    match), so fused/async lowering can't double-count."""
    out = []
    for m in _INSTR.finditer(hlo_text):
        line = hlo_text[m.start():hlo_text.find("\n", m.start())]
        sharding = _SHARDING.search(line)
        op_name = _OP_NAME.search(line)
        out.append({
            "op": m.group("op").replace("-start", ""),
            "rtype": m.group("rtype"),
            "bytes": shape_bytes(m.group("rtype")),
            "sharding": sharding.group(1) if sharding else None,
            "op_name": op_name.group(1) if op_name else None,
        })
    return out


def count_alias_entries(hlo_text: str) -> int:
    """Entries of the module-header ``input_output_alias={...}`` table —
    one per donated (parameter, output) pair in the compiled executable."""
    start = hlo_text.find("input_output_alias={")
    if start < 0:
        return 0
    i = hlo_text.find("{", start)
    depth, j = 0, i
    while j < len(hlo_text):
        if hlo_text[j] == "{":
            depth += 1
        elif hlo_text[j] == "}":
            depth -= 1
            if depth == 0:
                break
        j += 1
    region = hlo_text[i:j + 1]
    # Each entry reads ``{out_index}: (param, {param_index}, kind)``.
    return len(re.findall(r"\}:\s*\(", region))


def expected_hlo_census(jaxpr_census: dict[str, int]) -> dict[str, int]:
    """Map a jaxpr-primitive census onto optimized-HLO opcode counts."""
    out: dict[str, int] = {}
    for prim, n in jaxpr_census.items():
        op = PRIM_TO_HLO_OP.get(prim)
        if op is not None:
            out[op] = out.get(op, 0) + n
    return out


# ---------------------------------------------------------------- verdicts --
def audit_reshard_census(name: str, instrs: list[dict],
                         expected: dict[str, int]) -> list[Finding]:
    """Pure verdict: extra optimized-HLO collectives beyond the
    jaxpr-declared counts are GSPMD-inserted reshards. Fewer is fine —
    XLA fuses and decomposes declared collectives."""
    findings = []
    actual: dict[str, list[dict]] = {}
    for ins in instrs:
        actual.setdefault(ins["op"], []).append(ins)
    for op in sorted(actual):
        got, want = len(actual[op]), expected.get(op, 0)
        if got <= want:
            continue
        examples = []
        for ins in actual[op][:3]:
            examples.append(
                f"{ins['rtype']} (~{ins['bytes']} bytes, sharding="
                f"{ins['sharding'] or 'unannotated'}, from "
                f"{ins['op_name'] or 'unattributed op'})")
        findings.append(Finding(
            "hlo-reshard-census", f"hlo:{name}/{op}",
            f"{got - want} {op} op(s) in the optimized module beyond the "
            f"{want} the jaxpr declares — GSPMD inserted reshard(s) for a "
            f"sharding mismatch the step never asked for: "
            f"{'; '.join(examples)}"))
    return findings


def audit_donation_survival(alias_entries: int, n_state_leaves: int,
                            where: str) -> list[Finding]:
    """Pure verdict: the compiled executable must alias one input-output
    pair per state leaf, or donation died in lowering."""
    if alias_entries >= n_state_leaves:
        return []
    return [Finding(
        "hlo-donation-survival", where,
        f"compiled executable aliases only {alias_entries} of "
        f"{n_state_leaves} train-state leaves (input_output_alias) — "
        f"donation survived tracing but died in lowering, so the state is "
        f"double-buffered in HBM")]


def audit_budget_entry(program: str, analysis: dict, entry: dict,
                       tolerance: float) -> list[Finding]:
    """Pure verdict (shared with the fixture tests): gate measured bytes
    against one budget entry with a two-sided band of
    ``max(budget*tolerance, MIN_SLACK_BYTES)``."""
    findings = []
    for fld in BUDGET_FIELDS:
        if fld not in entry:
            findings.append(Finding(
                "hlo-memory-budget", f"hlo:{program}/{fld}",
                f"budget entry has no {fld!r} — regenerate with "
                f"scripts/graftcheck.py --update-budgets"))
            continue
        budget = int(entry[fld])
        actual = int(analysis.get(fld, 0))
        slack = max(int(budget * tolerance), MIN_SLACK_BYTES)
        if actual > budget + slack:
            findings.append(Finding(
                "hlo-memory-budget", f"hlo:{program}/{fld}",
                f"{fld} regressed: compiled program needs {actual} bytes, "
                f"budget is {budget} (+{slack} tolerance) — an HBM "
                f"regression the chip would pay for; if intentional, "
                f"regenerate configs/hlo_budgets.json with "
                f"--update-budgets so the bump is a reviewed diff"))
        elif actual < budget - slack:
            findings.append(Finding(
                "hlo-memory-budget", f"hlo:{program}/{fld}",
                f"{fld} budget is stale: compiled program needs {actual} "
                f"bytes but the budget reserves {budget} (-{slack} "
                f"tolerance) — regenerate with --update-budgets so the "
                f"gate stays tight"))
    return findings


# ----------------------------------------------------------------- probes --
_COMPILED_CACHE: dict[tuple[str, str], dict] = {}


def probe_config_digest(name: str) -> str:
    """Digest of the probe's effective config — budgets record it so a
    probe-config edit without a budget regeneration is detectable."""
    if name == "serve":
        cfg = jp._merge(jp._BASE, {"serve": {"probe": "forward"}})
    else:
        cfg = jp._merge(jp._BASE, jp.PROBE_CONFIGS[name])
    return hashlib.sha256(
        json.dumps(cfg, sort_keys=True).encode()).hexdigest()[:16]


def get_compiled(ctx: RepoContext, name: str) -> dict:
    """Compile (once per process) a probe's step — or the serve forward
    for ``name="serve"`` — and keep ``{"text", "analysis", "mesh"}``."""
    key = (str(ctx.root), name)
    if key in _COMPILED_CACHE:
        return _COMPILED_CACHE[key]
    if name == "serve":
        compiled, mesh_desc = _compile_serve_forward(ctx)
    else:
        probe = jp.get_probe(ctx, name)
        step = probe["builder"].make_train_step(probe["batch"])
        compiled = step.lower(probe["state_shapes"], probe["batch"]).compile()
        mesh_desc = ",".join(
            f"{a}={s}" for a, s in probe["builder"].mesh.shape.items()
            if s > 1)
    from distributed_tensorflow_framework_tpu.core import memstats
    entry = {
        "text": compiled.as_text(),
        "analysis": memstats.compiled_memory_analysis(compiled),
        "mesh": mesh_desc,
    }
    _COMPILED_CACHE[key] = entry
    return entry


def _compile_serve_forward(ctx: RepoContext):
    """The real serving path: serve/engine.py's ``make_forward`` over the
    dp-only serving mesh, params replicated, batch sharded over data —
    exactly what the standing engine jits."""
    jax = jp._require_runtime(ctx)
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distributed_tensorflow_framework_tpu.core.config import load_config
    from distributed_tensorflow_framework_tpu.core.mesh import batch_spec
    from distributed_tensorflow_framework_tpu.models import get_model
    from distributed_tensorflow_framework_tpu.serve.engine import (
        make_forward,
        serving_mesh,
    )
    from distributed_tensorflow_framework_tpu.train.step import model_inputs

    cfg = load_config(base=jp._merge(jp._BASE, {}))
    mesh = serving_mesh(-1)
    model = get_model(cfg.model, bn_axis_name=None, mesh=mesh)
    replicated = NamedSharding(mesh, P())
    image = jax.ShapeDtypeStruct(
        (64, 28, 28, 1), jnp.float32,
        sharding=NamedSharding(mesh, batch_spec(mesh)))
    var_shapes = jax.eval_shape(
        lambda im: model.init(jax.random.PRNGKey(0), im, train=False), image)
    variables = jax.tree.map(
        lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=replicated),
        var_shapes)
    inputs = model_inputs("image", {"image": image})
    compiled = make_forward(model, mesh).lower(variables, inputs).compile()
    return compiled, f"data={mesh.devices.size}"


# ---------------------------------------------------------------- budgets --
def budgets_path(ctx: RepoContext) -> pathlib.Path:
    return ctx.root / BUDGETS_RELPATH


def load_budgets(path: pathlib.Path) -> dict:
    data = json.loads(path.read_text())
    if data.get("schema") != BUDGETS_SCHEMA:
        raise ValueError(
            f"{path} has schema {data.get('schema')!r}, want "
            f"{BUDGETS_SCHEMA!r}")
    return data


def compute_budgets(ctx: RepoContext) -> dict:
    """Fresh-compile every budgeted program and assemble the budgets file
    content, with enough provenance (jax version, mesh, probe-config
    digest) that a budget bump is a reviewable, attributable diff."""
    import jax
    programs = {}
    for program, probe_name in BUDGET_PROGRAMS.items():
        compiled = get_compiled(ctx, probe_name)
        analysis = compiled["analysis"]
        if analysis is None:
            raise RuntimeError(
                f"memory_analysis unavailable for {program} — cannot "
                f"write a budget from nothing")
        entry = {fld: int(analysis[fld]) for fld in BUDGET_FIELDS}
        entry["mesh"] = compiled["mesh"]
        entry["config_sha256"] = probe_config_digest(probe_name)
        programs[program] = entry
    return {
        "schema": BUDGETS_SCHEMA,
        "provenance": {
            "jax": jax.__version__,
            "backend": "cpu",
            "device_count": jax.device_count(),
            "generated_by": "scripts/graftcheck.py --update-budgets",
        },
        "tolerance_frac": DEFAULT_TOLERANCE,
        "programs": programs,
    }


def write_budgets(ctx: RepoContext, path: pathlib.Path | None = None) -> str:
    path = path or budgets_path(ctx)
    data = compute_budgets(ctx)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(data, indent=2, sort_keys=True) + "\n")
    return str(path)


# ----------------------------------------------------------------- passes --
@register(
    "hlo-reshard-census", LAYER_HLO,
    "compile the shard_map probes and require every optimized-HLO "
    "collective to map to a jaxpr-declared one — extras are "
    "GSPMD-inserted reshards, reported with shape/bytes/sharding",
    anchors=("*/parallel/*.py", "*/train/step.py", "*/models/*.py"))
def reshard_census_pass(ctx: RepoContext) -> list[Finding]:
    findings = []
    for name in CENSUS_PROBES:
        probe = jp.get_probe(ctx, name)
        expected = expected_hlo_census(jp.collective_census(probe["jaxpr"]))
        if not expected:
            findings.append(Finding(
                "hlo-reshard-census", f"hlo:{name}",
                f"probe {name} declares no jaxpr collectives — the census "
                f"baseline is vacuous; probe config drifted",
                severity=SEVERITY_INTERNAL))
            continue
        instrs = collect_collectives(get_compiled(ctx, name)["text"])
        findings.extend(audit_reshard_census(name, instrs, expected))
    # The serve forward runs replicated-params over a dp-only mesh: ANY
    # collective in its optimized module is compiler-inserted.
    instrs = collect_collectives(get_compiled(ctx, "serve")["text"])
    findings.extend(audit_reshard_census("serve_forward", instrs, {}))
    return findings


@register(
    "hlo-donation-survival", LAYER_HLO,
    "compile the train-step probes and require one input_output_alias "
    "entry per state leaf in the executable (donation that dies in "
    "lowering doubles the state HBM footprint)",
    anchors=("*/train/step.py", "*/train/state.py"))
def donation_survival_pass(ctx: RepoContext) -> list[Finding]:
    findings = []
    for name in DONATION_PROBES:
        probe = jp.get_probe(ctx, name)
        entries = count_alias_entries(get_compiled(ctx, name)["text"])
        findings.extend(audit_donation_survival(
            entries, probe["n_state_leaves"], f"hlo:{name}/make_train_step"))
    return findings


@register(
    "hlo-memory-budget", LAYER_HLO,
    "gate compiled.memory_analysis() bytes for every budgeted program "
    "against configs/hlo_budgets.json (two-sided tolerance band; "
    "regenerate with --update-budgets)",
    anchors=("configs/hlo_budgets.json", "*/train/step.py",
             "*/models/*.py", "*/serve/engine.py"))
def memory_budget_pass(ctx: RepoContext) -> list[Finding]:
    path = budgets_path(ctx)
    if not path.exists():
        return [Finding(
            "hlo-memory-budget", "hlo:budgets",
            f"no {BUDGETS_RELPATH} — the memory gate is vacuous; run "
            f"scripts/graftcheck.py --update-budgets and commit the file",
            severity=SEVERITY_INTERNAL)]
    try:
        budgets = load_budgets(path)
    except (ValueError, json.JSONDecodeError) as exc:
        return [Finding(
            "hlo-memory-budget", "hlo:budgets",
            f"unreadable {BUDGETS_RELPATH}: {exc}",
            severity=SEVERITY_INTERNAL)]
    import jax
    findings = []
    recorded_jax = budgets.get("provenance", {}).get("jax")
    if recorded_jax != jax.__version__:
        # Byte figures legitimately shift across compiler versions:
        # regeneration is the fix, gating against them is noise.
        return [Finding(
            "hlo-memory-budget", "hlo:budgets",
            f"budgets were generated under jax {recorded_jax}, this run "
            f"is jax {jax.__version__} — regenerate with --update-budgets "
            f"so the drift lands as a reviewed diff")]
    tolerance = float(budgets.get("tolerance_frac", DEFAULT_TOLERANCE))
    programs = budgets.get("programs", {})
    for program, probe_name in BUDGET_PROGRAMS.items():
        entry = programs.get(program)
        if entry is None:
            findings.append(Finding(
                "hlo-memory-budget", f"hlo:{program}",
                f"no budget entry for {program} — run --update-budgets"))
            continue
        if entry.get("config_sha256") != probe_config_digest(probe_name):
            findings.append(Finding(
                "hlo-memory-budget", f"hlo:{program}",
                f"probe config changed since the budget for {program} was "
                f"generated (config_sha256 mismatch) — regenerate with "
                f"--update-budgets"))
            continue
        compiled = get_compiled(ctx, probe_name)
        if compiled["analysis"] is None:
            findings.append(Finding(
                "hlo-memory-budget", f"hlo:{program}",
                "compiled.memory_analysis() returned nothing on this "
                "backend — the gate cannot run",
                severity=SEVERITY_INTERNAL))
            continue
        findings.extend(audit_budget_entry(
            program, compiled["analysis"], entry, tolerance))
    return findings
