"""Layer-2 (jaxpr/trace) passes.

These audits do not read source text — they jit-trace the REAL train step
on the 8-device CPU mesh and inspect what the compiler will actually be
handed:

  * ``jaxpr-donation`` — lower the jit train step and count
    ``tf.aliasing_output`` markers: every state leaf must be donor-aliased
    (``donate_argnums=(0,)``), or the optimizer doubles its HBM footprint.
  * ``jaxpr-f32-upcast`` — walk the ClosedJaxpr of a bf16-configured step
    and flag ``convert_element_type`` ops that widen bf16/int8 tensors to
    f32 *feeding a dot/conv* (matmuls silently running in f32 defeat the
    mixed-precision config; intentional widenings carry a suppression).
  * ``jaxpr-collective-census`` — trace shard_map steps under
    ``collectives.tally()`` and require the jaxpr's collective-op counts to
    equal what the tally rows predict, in BOTH directions: an op the tally
    missed is an unaccounted wire transfer (the int8-compression numbers
    are benchmarked on that ledger), a tally row with no op is fiction.

Probes are traced once per process and memoized (``_PROBE_CACHE``) so the
tier-1 self-audit and the dedicated tests share the work. jax is imported
lazily so AST-only runs never pay for it.
"""

from __future__ import annotations

import pathlib
import sys

from tools.graftcheck.context import DEFAULT_PACKAGE, RepoContext
from tools.graftcheck.findings import Finding
from tools.graftcheck.registry import LAYER_JAXPR, register

ALIAS_MARKER = "tf.aliasing_output"

COLLECTIVE_PRIMS = frozenset({
    "psum", "all_gather", "ppermute", "all_to_all", "reduce_scatter",
    "pmin", "pmax",
})

# Tally kind → jaxpr primitives ONE wrapper call emits, for the wire
# formats the census probes below are configured with (full-precision
# gathers/scatters; int8 only via the q8 grad kinds, which emit two
# primitives each: payload + block scales).
KIND_TO_PRIMS: dict[str, tuple[tuple[str, int], ...]] = {
    "allreduce_grads_pmean": (("psum", 1),),          # pmean lowers to psum
    "allreduce_grads_pmean_narrow": (("psum", 1),),
    "allreduce_grads_scatter_f32": (("reduce_scatter", 1),),
    "allreduce_grads_gather_narrow": (("all_gather", 1),),
    "allreduce_grads_q8_scatter": (("all_to_all", 2),),
    "allreduce_grads_q8_gather": (("all_gather", 2),),
    "psum": (("psum", 1),),
    "pmean": (("psum", 1),),
    "all_gather": (("all_gather", 1),),
    "reduce_scatter": (("reduce_scatter", 1),),
    "ppermute": (("ppermute", 1),),
    "zero_reduce_scatter": (("reduce_scatter", 1),),  # psum_scatter prim name
    "zero_all_gather": (("all_gather", 1),),
}

# ----------------------------------------------------------------- probes --
_BASE = {
    "name": "graftcheck-probe",
    "model": {"name": "lenet5", "num_classes": 10, "dtype": "float32"},
    "data": {"name": "synthetic_images", "global_batch_size": 64,
             "image_size": 28, "channels": 1},
    "optimizer": {"name": "sgd_momentum", "learning_rate": 0.05},
    "train": {"total_steps": 5, "spmd_mode": "jit"},
}

PROBE_CONFIGS: dict[str, dict] = {
    # Donation audit: the plain jit path (train/step.py make_train_step).
    "jit_f32": {},
    # Upcast audit: same step with a bf16 model — every matmul should run
    # in bf16 except the deliberately-f32 logits head.
    "jit_bf16": {"model": {"dtype": "bfloat16"}},
    # Upcast audit B: model.dtype stays float32 but the PRECISION POLICY
    # layer (core/config.py PrecisionConfig) overrides the compute dtype
    # — proves precision.activation_dtype actually reaches the layers
    # (if the override were dropped the trace would be all-f32 and the
    # pass would find no logits-head widening, failing the dedicated
    # test rather than shipping a silent no-op knob).
    "jit_bf16_policy": {"precision": {"activation_dtype": "bf16"}},
    # Census A: explicit dp×fsdp collectives (grad pmean + param gathers).
    "shard_dp_fsdp": {"mesh": {"data": 4, "fsdp": 2},
                      "train": {"spmd_mode": "shard_map"}},
    # Census B: int8 block-scaled all-reduce with error feedback — the
    # probe that pins the q8 kinds to 2 wire ops each.
    "shard_q8_ef": {"mesh": {"data": 8},
                    "parallel": {"collective_dtype": "int8"},
                    "train": {"spmd_mode": "shard_map"}},
    # Census C: ZeRO weight-update sharding (bucketed RS/AG + the shard
    # grad-norm psum).
    "shard_zero": {"mesh": {"data": 8},
                   "optimizer": {"zero_sharding": "shard_map"},
                   "train": {"spmd_mode": "shard_map"}},
    # Census D: the fused donated optimizer update
    # (precision.fused_update) — the optax apply moves INTO the bucketed
    # reverse-layer walk (parallel/zero.fused_update_walk), so the probe
    # pins that fusing changes WHERE the update runs, not what goes on
    # the wire: collective kinds and counts must stay identical to the
    # unfused shard_zero probe, and the compiled module must keep at
    # least as many donation aliases (hlo_passes.DONATION_PROBES).
    "shard_zero_fused": {"mesh": {"data": 8},
                         "optimizer": {"zero_sharding": "shard_map"},
                         "train": {"spmd_mode": "shard_map"},
                         "precision": {"fused_update": True}},
}

CENSUS_PROBES = ("shard_dp_fsdp", "shard_q8_ef", "shard_zero",
                 "shard_zero_fused")

_PROBE_CACHE: dict[tuple[str, str], dict] = {}


def _merge(base: dict, over: dict) -> dict:
    out = {k: dict(v) if isinstance(v, dict) else v for k, v in base.items()}
    for k, v in over.items():
        if isinstance(v, dict):
            out.setdefault(k, {})
            out[k] = {**out[k], **v}
        else:
            out[k] = v
    return out


def _require_runtime(ctx: RepoContext):
    """Import jax + the package; the CLI shim / tests set the CPU-mesh env
    before jax initializes. Raises RuntimeError on an unusable runtime
    (surfaced by the runner as an internal-error finding)."""
    if ctx.package != DEFAULT_PACKAGE or not ctx.pkg_dir.is_dir():
        raise RuntimeError(
            "jaxpr passes trace the real train step and only run against "
            f"the {DEFAULT_PACKAGE} package (got {ctx.package!r})")
    root = str(ctx.root)
    if root not in sys.path:
        sys.path.insert(0, root)
    import jax
    jax.config.update("jax_platforms", "cpu")
    n = jax.device_count()
    if n != 8:
        raise RuntimeError(
            f"jaxpr passes need the 8-device CPU mesh "
            f"(XLA_FLAGS=--xla_force_host_platform_device_count=8 before "
            f"jax import); got {n} devices")
    return jax


def get_probe(ctx: RepoContext, name: str) -> dict:
    """Build (once per process) the traced/lowered artifacts for a probe:

    ``n_state_leaves`` always; ``alias_count`` for jit probes (from the
    lowered StableHLO text); ``jaxpr`` (ClosedJaxpr) for all probes;
    ``tally_calls`` (kind → call count) for shard_map probes.
    """
    key = (str(ctx.root), name)
    if key in _PROBE_CACHE:
        return _PROBE_CACHE[key]
    jax = _require_runtime(ctx)
    import jax.numpy as jnp
    from distributed_tensorflow_framework_tpu.core.config import load_config
    from distributed_tensorflow_framework_tpu.core.mesh import create_mesh
    from distributed_tensorflow_framework_tpu.parallel import collectives as coll
    from distributed_tensorflow_framework_tpu.train.step import StepBuilder

    cfg = load_config(base=_merge(_BASE, PROBE_CONFIGS[name]))
    mesh = create_mesh(cfg.mesh)
    sb = StepBuilder(cfg, mesh)
    batch = {"image": jax.ShapeDtypeStruct((64, 28, 28, 1), jnp.float32),
             "label": jax.ShapeDtypeStruct((64,), jnp.int32)}
    seed = jax.ShapeDtypeStruct((1,), jnp.uint32)
    state_shapes = jax.eval_shape(sb._create_state, seed, batch)
    probe: dict = {
        "config": cfg,
        "builder": sb,
        "batch": batch,
        "state_shapes": state_shapes,
        "n_state_leaves": len(jax.tree.leaves(state_shapes)),
    }
    with coll.tally() as t:
        step = sb.make_train_step(batch)
        traced = step.trace(state_shapes, batch)
    probe["jaxpr"] = traced.jaxpr
    probe["tally_calls"] = dict(t.calls)
    if name.startswith("jit"):
        probe["alias_count"] = count_output_aliases(
            step.lower(state_shapes, batch).as_text())
    _PROBE_CACHE[key] = probe
    return probe


# ---------------------------------------------------------------- walkers --
def iter_eqns(jaxpr):
    """Depth-first over a Jaxpr and every sub-jaxpr in eqn params
    (pjit/shard_map/scan/cond bodies)."""
    for eqn in jaxpr.eqns:
        yield eqn
        for v in eqn.params.values():
            for item in (v if isinstance(v, (list, tuple)) else [v]):
                if hasattr(item, "jaxpr"):        # ClosedJaxpr
                    yield from iter_eqns(item.jaxpr)
                elif hasattr(item, "eqns"):       # Jaxpr
                    yield from iter_eqns(item)


def count_output_aliases(stablehlo_text: str) -> int:
    """Donated inputs show up as ``tf.aliasing_output`` attributes on the
    entry computation's parameters."""
    return stablehlo_text.count(ALIAS_MARKER)


def collective_census(closed_jaxpr) -> dict[str, int]:
    counts: dict[str, int] = {}
    for eqn in iter_eqns(closed_jaxpr.jaxpr):
        if eqn.primitive.name in COLLECTIVE_PRIMS:
            counts[eqn.primitive.name] = counts.get(eqn.primitive.name, 0) + 1
    return counts


def expected_census(tally_calls: dict[str, int]
                    ) -> tuple[dict[str, int], list[str]]:
    """Predict the jaxpr collective counts from tally rows; unknown kinds
    are returned separately (a new wrapper kind must be added to
    KIND_TO_PRIMS before it can pass the census)."""
    expected: dict[str, int] = {}
    unknown = []
    for kind, n in tally_calls.items():
        if kind not in KIND_TO_PRIMS:
            unknown.append(kind)
            continue
        for prim, mult in KIND_TO_PRIMS[kind]:
            expected[prim] = expected.get(prim, 0) + mult * n
    return expected, unknown


def collect_upcasts(closed_jaxpr) -> list[tuple[str, str]]:
    """(consumer_prim, name_stack) for each convert_element_type that
    widens a bf16/int8 tensor to f32 and feeds a dot/conv."""
    import jax.numpy as jnp
    narrow = (jnp.bfloat16, jnp.int8)
    hits: list[tuple[str, str]] = []

    def rec(jaxpr):
        converts: set = set()
        for eqn in jaxpr.eqns:
            if (eqn.primitive.name == "convert_element_type"
                    and getattr(eqn.invars[0].aval, "dtype", None) in narrow
                    and eqn.params.get("new_dtype") == jnp.float32):
                converts.add(eqn.outvars[0])
            elif eqn.primitive.name in ("dot_general", "conv_general_dilated"):
                if any(iv in converts for iv in eqn.invars):
                    hits.append((eqn.primitive.name,
                                 str(eqn.source_info.name_stack)))
            for v in eqn.params.values():
                for item in (v if isinstance(v, (list, tuple)) else [v]):
                    if hasattr(item, "jaxpr"):
                        rec(item.jaxpr)
                    elif hasattr(item, "eqns"):
                        rec(item)

    rec(closed_jaxpr.jaxpr)
    return hits


# ----------------------------------------------------------------- passes --
def audit_donation(alias_count: int, n_state_leaves: int,
                   where: str) -> list[Finding]:
    """Pure verdict (shared with the seeded-regression test): every state
    leaf must be donor-aliased to an output."""
    if alias_count >= n_state_leaves:
        return []
    return [Finding(
        "jaxpr-donation", where,
        f"only {alias_count} of {n_state_leaves} train-state leaves are "
        f"donor-aliased ({ALIAS_MARKER}) in the lowered step — "
        f"donate_argnums=(0,) was dropped or defeated, doubling the "
        f"optimizer-state HBM footprint")]


@register(
    "jaxpr-donation", LAYER_JAXPR,
    "lower the jit train step and require every state leaf donor-aliased "
    "(donation elision doubles the state HBM footprint)",
    anchors=("*/train/step.py", "*/train/state.py"))
def donation_pass(ctx: RepoContext) -> list[Finding]:
    probe = get_probe(ctx, "jit_f32")
    return audit_donation(probe["alias_count"], probe["n_state_leaves"],
                          "trace:jit_f32/make_train_step")


@register(
    "jaxpr-f32-upcast", LAYER_JAXPR,
    "trace a bf16-configured step and flag bf16/int8→f32 widenings that "
    "feed a dot/conv (silent f32 matmuls defeat the mixed-precision "
    "config); intentional widenings carry suppressions",
    anchors=("*/train/step.py", "*/models/*.py", "*/train/losses.py"))
def f32_upcast_pass(ctx: RepoContext) -> list[Finding]:
    findings = []
    seen = set()
    # Two routes to a bf16 step, both audited: model.dtype=bfloat16 and
    # the precision-policy override (precision.activation_dtype=bf16 over
    # an f32 model config). The where strings are probe-agnostic on
    # purpose — the same logits-head suppression covers the identical
    # widening in both traces.
    for probe_name in ("jit_bf16", "jit_bf16_policy"):
        probe = get_probe(ctx, probe_name)
        for prim, stack in collect_upcasts(probe["jaxpr"]):
            where = f"trace:{stack}"
            if (prim, where) in seen:
                continue
            seen.add((prim, where))
            findings.append(Finding(
                "jaxpr-f32-upcast", where,
                f"{prim} consumes a bf16/int8 tensor widened to f32 at "
                f"{stack} — the matmul runs full-precision despite the "
                f"bf16 compute config (suppress with a justification if "
                f"intentional)"))
    return findings


@register(
    "jaxpr-collective-census", LAYER_JAXPR,
    "trace shard_map steps under collectives.tally() and require jaxpr "
    "collective-op counts == tally-predicted counts, both directions "
    "(the wire-byte ledger must account for every collective)",
    anchors=("*/parallel/*.py", "*/train/step.py"))
def collective_census_pass(ctx: RepoContext) -> list[Finding]:
    findings = []
    for name in CENSUS_PROBES:
        probe = get_probe(ctx, name)
        actual = collective_census(probe["jaxpr"])
        expected, unknown = expected_census(probe["tally_calls"])
        for kind in unknown:
            findings.append(Finding(
                "jaxpr-collective-census", f"trace:{name}/{kind}",
                f"tally kind {kind!r} is not in KIND_TO_PRIMS — teach the "
                f"census the wrapper's wire ops before shipping it",
                severity="internal-error"))
        for prim in sorted(set(actual) | set(expected)):
            a, e = actual.get(prim, 0), expected.get(prim, 0)
            if a == e:
                continue
            if a > e:
                msg = (f"{a - e} {prim} op(s) in the traced step have no "
                       f"CollectiveTally row (probe {name}: jaxpr={a}, "
                       f"tally predicts {e}) — an untallied collective is "
                       f"an unaccounted wire transfer")
            else:
                msg = (f"tally predicts {e} {prim} op(s) but the jaxpr has "
                       f"{a} (probe {name}) — a tally row with no op "
                       f"overstates wire bytes")
            findings.append(Finding(
                "jaxpr-collective-census", f"trace:{name}/{prim}", msg))
    return findings
