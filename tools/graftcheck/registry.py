"""Pass registry — a pass is a named callable ``fn(ctx) -> list[Finding]``.

Registration happens at import of the pass modules (tools/graftcheck
``__init__``). ``anchors`` are repo-relative glob patterns naming the files
a repo-wide pass derives its verdict from: in ``--changed`` mode a
repo-wide pass runs only when one of its anchors changed, while per-file
passes (empty anchors) simply restrict their scan to the changed files.
"""

from __future__ import annotations

import fnmatch
from dataclasses import dataclass
from typing import Callable

LAYER_AST = "ast"
LAYER_JAXPR = "jaxpr"
LAYER_HLO = "hlo"
LAYERS = (LAYER_AST, LAYER_JAXPR, LAYER_HLO)

# Layers that trace/compile the real step (seconds, not milliseconds) —
# skipped in --changed mode unless --trace opts them back in.
TRACE_LAYERS = (LAYER_JAXPR, LAYER_HLO)


@dataclass
class PassInfo:
    pass_id: str
    layer: str
    description: str
    fn: Callable
    anchors: tuple[str, ...] = ()   # () → per-file pass

    def relevant_for_changed(self, changed: set[str]) -> bool:
        if not self.anchors:
            return True  # per-file passes self-restrict to changed files
        return any(
            fnmatch.fnmatch(path, pat)
            for path in changed for pat in self.anchors
        )


PASSES: dict[str, PassInfo] = {}


def register(pass_id: str, layer: str, description: str,
             anchors: tuple[str, ...] = ()):
    if layer not in LAYERS:
        raise ValueError(f"unknown layer {layer!r} for pass {pass_id!r}")

    def deco(fn):
        if pass_id in PASSES:
            raise ValueError(f"duplicate pass id {pass_id!r}")
        PASSES[pass_id] = PassInfo(pass_id, layer, description, fn, anchors)
        return fn

    return deco


def get_pass(pass_id: str) -> PassInfo:
    try:
        return PASSES[pass_id]
    except KeyError:
        raise KeyError(
            f"unknown pass {pass_id!r}; known: {sorted(PASSES)}") from None


def passes_for_layer(layer: str) -> list[PassInfo]:
    return [p for p in PASSES.values() if p.layer == layer]
