#!/usr/bin/env python
"""Root entrypoint — mirrors the reference's top-level train.py.

See distributed_tensorflow_framework_tpu/cli/train.py for flags.
"""

import sys

from distributed_tensorflow_framework_tpu.cli.train import main

if __name__ == "__main__":
    sys.exit(main())
